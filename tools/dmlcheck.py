#!/usr/bin/env python3
"""dmlcheck — static analysis for this repo's distributed-correctness
invariants.

Usage::

    python tools/dmlcheck.py [ROOT] [--json] [--rules DML001,DML004]
                             [--baseline FILE | --no-baseline]
                             [--layer2] [--layer3 [--quick]]
                             [--mutate NAME,NAME] [--repro-dir DIR]
                             [--replay FILE] [--list-rules]
                             [--write-baseline]

Layer 1 (default, stdlib-only, no jax import, <10 s): the AST rules in
``distributed_machine_learning_tpu/analysis/ast_rules.py`` over the
package + tools + tests sources.  ``--layer2`` additionally compiles
the ring and zero1 train steps on an 8-virtual-device CPU mesh and runs
the jaxpr/HLO audit passes (donation taken, no critical-path
all-gather, wire-byte accounting) — slower, imports jax.  ``--layer3``
runs the deterministic interleaving explorer over the gang-transport
scenarios (``analysis/interleave.py``): ``--quick`` keeps it to the
exhaustive small configs (CI-sized, <30 s); a violated invariant
(DML301, DML302 for deadlocks) carries a minimized schedule trace and
a reproducer file ``--replay`` re-runs bit-for-bit.  ``--mutate``
re-introduces a known-bug seed (the mutation-test gate).

Exit codes: 0 clean (every finding baselined, no stale baseline
entries), 1 non-baselined ERROR findings or stale entries, 2 usage /
malformed-baseline errors.  Advisory findings are always reported but
never fail the run.  ``--json`` prints one machine-readable verdict
dict (same philosophy as ``ckpt_verify --json``).

Baseline workflow: fix the finding if you can; when the flagged idiom
is deliberate, add an entry to ``dmlcheck_baseline.json`` with a
written justification (entries without one fail with exit 2), matched
on (rule, file, substring-of-the-flagged-line).  Stale entries —
suppressing nothing — fail the run so the baseline only shrinks.
``--write-baseline`` prints a skeleton for the current NEW findings to
paste in (justifications left for you to write; an empty one will not
pass).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Layer 1 must stay importable without jax: only analysis.ast_rules /
# analysis.findings (stdlib-only by construction) are imported here;
# program_audit is imported inside --layer2.
from distributed_machine_learning_tpu.analysis.ast_rules import (  # noqa: E402,E501
    RULES,
    run_layer1,
)
from distributed_machine_learning_tpu.analysis.findings import (  # noqa: E402,E501
    BaselineError,
    apply_baseline,
    findings_to_json,
    load_baseline,
)

BASELINE_NAME = "dmlcheck_baseline.json"


def _run_layer2():
    # The CPU mesh needs the 8-way host-platform split BEFORE jax
    # initializes a backend (shared helper; Layer 1 must stay jax-free,
    # so this import lives inside the layer-2 branch only).
    from distributed_machine_learning_tpu.runtime.mesh import (
        ensure_host_devices,
    )

    ensure_host_devices(8)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from distributed_machine_learning_tpu.analysis.program_audit import (
        run_layer2,
    )

    return run_layer2()


def _run_replay(path: str, as_json: bool) -> int:
    """Re-run the exact interleaving a layer-3 reproducer recorded.
    Exit 1 when the failure reproduces (the deterministic-CI-failure
    contract: two replays of one file fail identically), 0 when the
    schedule now passes (the bug is fixed — delete the file), 2 on a
    malformed/unknown reproducer."""
    from distributed_machine_learning_tpu.analysis.interleave import (
        format_trace,
        replay_file,
    )

    try:
        verdict = replay_file(path)
    except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
        print(f"dmlcheck: bad reproducer {path}: {e}", file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps(verdict, indent=1))
    else:
        print(f"replay {verdict['scenario']} ({verdict['size']}"
              + (f", mutate={verdict['mutate']}" if verdict["mutate"]
                 else "") + "):")
        print(format_trace(verdict["trace"]))
        for v in verdict["violations"]:
            print(f"  VIOLATION: {v}")
        if not verdict["reproduced"]:
            print("  schedule passes now — fixed; delete the "
                  "reproducer")
    return 1 if verdict["reproduced"] else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("root", nargs="?", default=REPO,
                        help="repo root to scan (default: this repo)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable verdict on stdout")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run "
                             "(default: all Layer-1 rules)")
    parser.add_argument("--baseline", default=None,
                        help=f"suppression file (default: "
                             f"ROOT/{BASELINE_NAME})")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline (report everything)")
    parser.add_argument("--layer2", action="store_true",
                        help="also compile train steps and run the "
                             "jaxpr/HLO audit passes (imports jax)")
    parser.add_argument("--layer3", action="store_true",
                        help="also run the deterministic interleaving "
                             "explorer over the gang-transport "
                             "scenarios (DML301/DML302)")
    parser.add_argument("--quick", action="store_true",
                        help="layer 3: exhaustive small configs only "
                             "(CI-sized, <30s)")
    parser.add_argument("--mutate", default=None,
                        help="layer 3: comma-separated known-bug "
                             "seeds to re-introduce (mutation-test "
                             "gate); see analysis/interleave.py "
                             "MUTATIONS")
    parser.add_argument("--repro-dir", default=None,
                        help="layer 3: directory for reproducer files "
                             "(default: <tmp>/dmlcheck-repros)")
    parser.add_argument("--replay", default=None, metavar="FILE",
                        help="re-run the exact interleaving a "
                             "reproducer recorded, print the "
                             "annotated trace, exit 1 if it still "
                             "fails (deterministic)")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--write-baseline", action="store_true",
                        help="print a baseline skeleton for the "
                             "current NEW findings and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for r in RULES.values():
            print(f"{r.id}  {r.title}")
            print(f"        incident: {r.incident}")
        return 0

    if args.replay:
        return _run_replay(args.replay, as_json=args.json)

    LAYER2_RULES = {"DML101", "DML102", "DML103", "DML104"}
    LAYER3_RULES = {"DML301", "DML302"}
    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(RULES) - LAYER2_RULES - LAYER3_RULES
        if unknown:
            print(f"dmlcheck: unknown rule id(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2
        if rules & LAYER2_RULES and not args.layer2:
            # Without the pass actually running, a Layer-2-only filter
            # would report a false green verdict.
            print("dmlcheck: rule(s) "
                  f"{sorted(rules & LAYER2_RULES)} are Layer-2 program "
                  "audits — add --layer2 to run them", file=sys.stderr)
            return 2
        if rules & LAYER3_RULES and not args.layer3:
            print("dmlcheck: rule(s) "
                  f"{sorted(rules & LAYER3_RULES)} are Layer-3 "
                  "interleaving checks — add --layer3 to run them",
                  file=sys.stderr)
            return 2
    if args.mutate and not args.layer3:
        print("dmlcheck: --mutate only applies to --layer3",
              file=sys.stderr)
        return 2

    root = os.path.abspath(args.root)
    rule_timings: dict = {}
    timing = {"layer1_s": 0.0, "layer2_s": 0.0, "layer3_s": 0.0,
              "rules": rule_timings}
    t0 = time.perf_counter()
    findings = run_layer1(
        root, rules=None if rules is None
        else {r for r in rules if r in RULES},
        timings=rule_timings)
    timing["layer1_s"] = round(time.perf_counter() - t0, 3)
    if args.layer2:
        t0 = time.perf_counter()
        l2 = _run_layer2()
        timing["layer2_s"] = round(time.perf_counter() - t0, 3)
        if rules is not None:
            l2 = [f for f in l2 if f.rule in rules]
        findings += l2
    layer3_stats = None
    if args.layer3:
        from distributed_machine_learning_tpu.analysis.interleave import (
            run_layer3,
        )

        mutate = tuple(m.strip() for m in (args.mutate or "").split(",")
                       if m.strip())
        repro_dir = args.repro_dir or os.path.join(
            tempfile.gettempdir(), "dmlcheck-repros")
        t0 = time.perf_counter()
        try:
            l3, layer3_stats = run_layer3(
                quick=args.quick, mutate=mutate, repro_dir=repro_dir)
        except ValueError as e:
            print(f"dmlcheck: {e}", file=sys.stderr)
            return 2
        timing["layer3_s"] = round(time.perf_counter() - t0, 3)
        for name, entry in layer3_stats["scenarios"].items():
            rule_timings[f"layer3:{name}"] = entry["seconds"]
        if rules is not None:
            l3 = [f for f in l3 if f.rule in rules]
        findings += l3

    baseline = []
    if not args.no_baseline:
        try:
            baseline = load_baseline(
                args.baseline or os.path.join(root, BASELINE_NAME))
        except BaselineError as e:
            print(f"dmlcheck: {e}", file=sys.stderr)
            return 2
    if rules is not None:
        # A --rules subset must not report the OTHER rules' baseline
        # entries as stale: only entries whose rule actually ran can be
        # judged used/unused.
        baseline = [e for e in baseline if e["rule"] in rules]
    new, suppressed, unused = apply_baseline(findings, baseline)
    advisories = [f for f in new if f.severity == "advisory"]
    errors = [f for f in new if f.severity != "advisory"]

    if args.write_baseline:
        skeleton = [{"rule": f.rule, "file": f.file,
                     "match": f.snippet or f.message,
                     "justification": ""} for f in errors]
        print(json.dumps({"suppressions": skeleton}, indent=2))
        return 0

    if args.json:
        payload = findings_to_json(
            new, suppressed, unused,
            rules_run=sorted(rules) if rules else sorted(RULES))
        payload["errors"] = len(errors)
        payload["advisories"] = len(advisories)
        payload["clean"] = not errors and not unused
        timing["rules"] = {k: round(v, 4)
                           for k, v in sorted(rule_timings.items())}
        payload["timing"] = timing
        if layer3_stats is not None:
            payload["layer3"] = layer3_stats
        print(json.dumps(payload, indent=1))
    else:
        for f in errors:
            print(f"{f.rule} {f.location()}: {f.message}")
            if f.snippet:
                print(f"    > {f.snippet}")
        for f in advisories:
            print(f"{f.rule} {f.location()} (advisory): {f.message}")
        for e in unused:
            print(f"STALE baseline entry (fixed? drop it): "
                  f"{e['rule']} {e['file']} ~ {e['match']!r}")
        print(f"dmlcheck: {len(errors)} error(s), "
              f"{len(advisories)} advisory, "
              f"{len(suppressed)} baselined, "
              f"{len(unused)} stale baseline entr(ies)")
    return 1 if (errors or unused) else 0


if __name__ == "__main__":
    raise SystemExit(main())
