"""Measure the tiered int8-KV-cache dispatch question (VERDICT r4 #7).

The int8 decode dispatch (``models/transformer.py``) always takes the
scale-folding einsum, which reads ALL S allocated cache slots; the
Pallas kernel's frontier clamp reads O(pos).  The einsum is ~2.8×
cheaper per byte (measured r4), so it loses only while pos/S < ~0.36 —
a transient early phase — and r4 dismissed a two-tier ``lax.switch``
as "not worth its compile cost" WITHOUT a number.  This bench produces
the numbers for both sides of that call:

1. per-step attention time, einsum vs int8-kernel, at a ladder of
   pos/S fill fractions (the kernel's O(pos) advantage vs the einsum's
   cheaper bytes — locates the real crossover);
2. the compile cost of a two-tier ``lax.cond`` decode program (the
   dispatch _INT8_TIERED_DISPATCH enables) vs the single-path program,
   at a realistic layer count (the cond is traced per layer).

Timing: the attention ops are µs-scale, far below even the VARIANCE of
the tunnel's per-dispatch RTT, so each measurement runs N data-dependent
iterations inside ONE jitted ``lax.scan`` (the step's output feeds the
next step's query — nothing can be hoisted or elided) and the per-op
time is the two-point slope over scan lengths (N vs 2N), which cancels
the single dispatch+fetch round-trip.  The first cut of this bench used
chained dispatches per op and read 100× RTT jitter, not op time.

Run on the TPU::

    python -m distributed_machine_learning_tpu.bench.int8_tier \
        --s-alloc 32768 --fracs 0.05,0.2,0.36,0.7,0.95
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def _time_op(op, q, *rest, reps: int = 3, iters: int = 200):
    """Per-op seconds for ``op(q, *rest) -> array shaped like q``: N
    data-dependent iterations inside one jitted scan (q threads
    through), per-op time from the (N vs 2N)-scan slope — see the
    module docstring for why chained dispatches cannot measure this."""
    from jax import lax

    def make(n):
        @jax.jit
        def run(q0, *r):
            def body(qc, _):
                return op(qc, *r).astype(q0.dtype), ()

            qn, _ = lax.scan(body, q0, None, length=n)
            return qn

        return run

    from distributed_machine_learning_tpu.bench.harness import (
        length_slope_fit,
    )

    def timed(n):
        run = make(n)
        jax.block_until_ready(run(q, *rest))  # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            np.asarray(run(q, *rest)[..., 0])  # fetch closes the timing
            best = min(best, time.perf_counter() - t0)
        return best

    # One slope fit for every bench (bench/harness.py): per-op seconds
    # from the N-vs-2N scan lengths, jitter-guarded.
    return length_slope_fit(timed, iters, 2 * iters)


def bench_attention_ladder(s_alloc: int, fracs, hkv: int, rep: int,
                           d: int, reps: int, chain: int):
    """Single-token int8 cached attention: einsum (full-S reads) vs the
    Pallas kernel (frontier-clamped O(pos) reads) at each fill
    fraction."""
    from distributed_machine_learning_tpu.models.transformer import (
        _cached_attention_quant,
    )
    from distributed_machine_learning_tpu.ops.pallas.decode_attention import (
        cached_flash_attention,
    )

    rng = np.random.default_rng(0)
    B, H = 1, hkv * rep
    q = jnp.asarray(rng.standard_normal((B, 1, H, d)), jnp.bfloat16)
    k_int = jnp.asarray(
        rng.integers(-127, 127, (B, hkv, s_alloc, d)), jnp.int8
    )
    v_int = jnp.asarray(
        rng.integers(-127, 127, (B, hkv, s_alloc, d)), jnp.int8
    )
    ks = jnp.asarray(rng.random((B, hkv, s_alloc)) * 0.01, jnp.float32)
    vs = jnp.asarray(rng.random((B, hkv, s_alloc)) * 0.01, jnp.float32)

    def einsum_op(q_, ki, ks_, vi, vs_, pos):
        return _cached_attention_quant(q_, ki, ks_, vi, vs_, pos)

    def kernel_op(q_, ki, ks_, vi, vs_, p0):
        return cached_flash_attention(q_, ki, vi, p0, k_scale=ks_,
                                      v_scale=vs_)

    rows = []
    for frac in fracs:
        pos = max(1, int(s_alloc * frac) - 1)
        positions = jnp.asarray([pos], jnp.int32)
        p0 = jnp.asarray(pos, jnp.int32)
        t_e = _time_op(einsum_op, q, k_int, ks, v_int, vs, positions,
                       reps=reps, iters=chain)
        t_k = _time_op(kernel_op, q, k_int, ks, v_int, vs, p0,
                       reps=reps, iters=chain)
        rows.append({
            "pos_over_S": round(frac, 3), "pos": pos,
            "einsum_us": round(t_e * 1e6, 1),
            "kernel_us": round(t_k * 1e6, 1),
            "kernel_wins": bool(t_k < t_e),
        })
        print(json.dumps({"metric": "int8_cache_attention_us", **rows[-1],
                          "s_alloc": s_alloc}), flush=True)
    return rows


def bench_switch_compile(s_alloc: int, n_layers: int, d_model: int,
                         n_heads: int, n_kv_heads: int):
    """Compile-time cost of the two-tier dispatch: a generate-shaped
    decode step whose attention is the per-layer ``lax.cond(kernel,
    einsum)`` that ``_INT8_TIERED_DISPATCH`` enables, vs the plain
    einsum-only program.  The cond's runtime price (both branches'
    code, one executed) rides along in the compiled-program
    comparison; what this measures is the COMPILE delta a server would
    pay per (batch, prompt-length) shape."""
    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state

    model = TransformerLM(
        vocab_size=32000, d_model=d_model, n_layers=n_layers,
        n_heads=n_heads, n_kv_heads=n_kv_heads,
        compute_dtype=jnp.bfloat16, kv_cache_dtype=jnp.int8,
    )
    params = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16) if p.dtype == jnp.float32 else p,
        init_lm_state(model).params,
    )
    from distributed_machine_learning_tpu.inference.generate import (
        make_generate_fn,
    )

    prompt = jnp.zeros((1, 128), jnp.int32)
    results = {}
    for tiered in (False, True):
        import distributed_machine_learning_tpu.models.transformer as tmod

        tmod._INT8_TIERED_DISPATCH = tiered
        fn = make_generate_fn(model, s_alloc - 256)
        t0 = time.perf_counter()
        lowered = jax.jit(
            lambda p, pr, k: fn(p, pr, k)
        ).lower(params, prompt, jax.random.PRNGKey(0))
        compiled = lowered.compile()
        dt = time.perf_counter() - t0
        results["tiered" if tiered else "plain"] = round(dt, 2)
        del compiled
        print(json.dumps({
            "metric": "int8_generate_compile_seconds",
            "tiered": tiered, "seconds": round(dt, 2),
            "n_layers": n_layers, "gen_tokens": s_alloc - 256,
        }), flush=True)
    tmod._INT8_TIERED_DISPATCH = False
    return results


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--s-alloc", dest="s_alloc", default=32768, type=int)
    p.add_argument("--fracs", default="0.05,0.2,0.36,0.7,0.95")
    p.add_argument("--hkv", default=8, type=int)
    p.add_argument("--rep", default=1, type=int,
                   help="query heads per KV head (GQA group)")
    p.add_argument("--head-dim", dest="head_dim", default=64, type=int)
    p.add_argument("--reps", default=3, type=int)
    p.add_argument("--chain", default=200, type=int,
               help="scan iterations per timed dispatch (per-op\n                    time is the N-vs-2N slope)")
    p.add_argument("--compile-layers", dest="compile_layers", default=8,
                   type=int)
    p.add_argument("--compile-d-model", dest="compile_d_model",
                   default=512, type=int)
    p.add_argument("--skip-compile", dest="skip_compile",
                   action="store_true")
    args = p.parse_args()
    fracs = [float(f) for f in args.fracs.split(",")]
    bench_attention_ladder(args.s_alloc, fracs, args.hkv, args.rep,
                           args.head_dim, args.reps, args.chain)
    if not args.skip_compile:
        # Same GQA shape as the ladder: H = hkv * rep query heads.
        bench_switch_compile(args.s_alloc, args.compile_layers,
                             args.compile_d_model, args.hkv * args.rep,
                             args.hkv)


if __name__ == "__main__":
    main()
