"""Pallas flash attention (interpret mode on the CPU mesh) vs the dense
reference — forward, backward, and inside the full model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.ops.pallas.flash_attention import (
    _dkv_blocks,
    _fwd_blocks,
    _pick,
    flash_self_attention,
)
from distributed_machine_learning_tpu.ops.ring_attention import (
    dense_self_attention,
)


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(69143)
    shape = (2, 64, 4, 16)  # [B, L, H, D]
    return tuple(
        jnp.asarray(rng.standard_normal(shape, dtype=np.float32)) for _ in range(3)
    )


def test_flash_matches_dense_forward(qkv):
    q, k, v = qkv
    np.testing.assert_allclose(
        np.asarray(flash_self_attention(q, k, v)),
        np.asarray(dense_self_attention(q, k, v)),
        rtol=1e-5,
        atol=1e-6,
    )


def test_block_picker():
    # Powers of two dividing L, capped at the measured-optimal 512 square
    # (see the sweep notes in _fwd_blocks/_dkv_blocks).
    assert _pick(48, 512) == 16
    assert _fwd_blocks(4096) == (512, 512)
    assert _dkv_blocks(4096) == (512, 512)
    assert _fwd_blocks(64) == (64, 64)
    assert _pick(17, 512) == 1  # prime-ish lengths degrade, don't crash


def test_auto_attn_policy():
    from distributed_machine_learning_tpu.models.transformer import _flash_wins

    assert not _flash_wins(256)  # below the measured crossover
    assert _flash_wins(512) and _flash_wins(4096) and _flash_wins(16384)
    # Sub-1k lengths not divisible by 512 degrade the blocks past the
    # thin @512 margin — dense keeps them.
    assert not _flash_wins(640) and not _flash_wins(768)
    assert not _flash_wins(1040)  # 16·65: pad overhead beats dense's 1.6×
    # From 2048 up the policy is TOTAL: every length dispatches flash
    # (padded when needed) because dense is ≥2× behind or uncompilable.
    assert _flash_wins(2050) and _flash_wins(16640) and _flash_wins(30000)
    # The ring upgrade stays native-tileable only (no pad path there).
    from distributed_machine_learning_tpu.models.transformer import (
        _ring_flash_wins,
    )

    assert _ring_flash_wins(4096) and not _ring_flash_wins(2050)


def test_flash_odd_length(qkv):
    # L=48: largest power-of-two divisor 16 < 128 → the kernel pads to
    # the next 512 multiple and slices back (Mosaic cannot tile a
    # 16-lane residual block).  Padding must be invisible: exact dense
    # parity, forward and backward.
    q, k, v = (a[:, :48] for a in qkv)
    from distributed_machine_learning_tpu.ops.pallas.flash_attention import (
        _needs_pad,
    )

    assert _needs_pad(48) and not _needs_pad(64) and not _needs_pad(16640)
    np.testing.assert_allclose(
        np.asarray(flash_self_attention(q, k, v)),
        np.asarray(dense_self_attention(q, k, v)),
        rtol=1e-5,
        atol=1e-6,
    )
    g = jnp.ones_like(q)
    _, flash_vjp = jax.vjp(flash_self_attention, q, k, v)
    _, dense_vjp = jax.vjp(dense_self_attention, q, k, v)
    for got, want, name in zip(flash_vjp(g), dense_vjp(g), "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5,
            err_msg=f"d{name} mismatch through the padded path",
        )


def test_flash_backward_matches_dense(qkv):
    q, k, v = qkv
    cot = jnp.asarray(
        np.random.default_rng(1).standard_normal(q.shape, dtype=np.float32)
    )

    def loss_flash(q, k, v):
        return jnp.sum(flash_self_attention(q, k, v) * cot)

    def loss_dense(q, k, v):
        return jnp.sum(dense_self_attention(q, k, v) * cot)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        )


def test_flash_model_matches_dense_model():
    tokens = jnp.asarray(
        np.random.default_rng(5).integers(0, 64, (2, 32)), jnp.int32
    )
    dense = TransformerLM(vocab_size=64, d_model=32, n_layers=2, n_heads=4)
    flash = TransformerLM(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, attn_impl="flash"
    )
    params = dense.init(jax.random.PRNGKey(0), tokens)["params"]
    ref = dense.apply({"params": params}, tokens)
    out = flash.apply({"params": params}, tokens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_flash_gqa_matches_repeated_dense(rng):
    """GQA-native flash (narrow K/V streamed via divided index maps) ==
    dense attention over explicitly repeated K/V — forward and all three
    gradients (dk/dv group-summed down to the narrow heads)."""
    B, L, H, Hkv, D = 2, 32, 8, 2, 8
    n_rep = H // Hkv
    q = jnp.asarray(rng.standard_normal((B, L, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, L, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, L, Hkv, D)), jnp.float32)
    g = jnp.asarray(rng.standard_normal((B, L, H, D)), jnp.float32)

    def rep(t):
        return jnp.repeat(t, n_rep, axis=2)

    def dense_ref(q, k, v):
        return dense_self_attention(q, rep(k), rep(v))

    out, flash_vjp = jax.vjp(flash_self_attention, q, k, v)
    ref, dense_vjp = jax.vjp(dense_ref, q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    for got, want, name in zip(flash_vjp(g), dense_vjp(g), "qkv"):
        assert got.shape == want.shape, name
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5,
            err_msg=f"d{name} mismatch",
        )
    with pytest.raises(ValueError, match="identical shapes"):
        flash_self_attention(q, k[:, :, :1], v)  # k/v head mismatch
    bad_kv = k[:, :, :1][:, :, [0, 0, 0]]  # 3 heads: does not divide 8
    with pytest.raises(ValueError, match="multiple of K/V heads"):
        flash_self_attention(q, bad_kv, bad_kv)


def test_flash_bf16_finite(qkv):
    q, k, v = (a.astype(jnp.bfloat16) for a in qkv)
    out = np.asarray(flash_self_attention(q, k, v), dtype=np.float32)
    assert np.isfinite(out).all()


def test_flash_backward_matches_dense_vjp(rng):
    # The Pallas backward (dq/dkv kernels recomputing from the saved
    # logsumexp) must match the dense XLA VJP on all three gradients.
    from distributed_machine_learning_tpu.ops.pallas.flash_attention import (
        flash_self_attention,
    )
    from distributed_machine_learning_tpu.ops.ring_attention import (
        dense_self_attention,
    )

    B, L, H, D = 2, 32, 2, 8
    q = jnp.asarray(rng.standard_normal((B, L, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, L, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, L, H, D)), jnp.float32)
    g = jnp.asarray(rng.standard_normal((B, L, H, D)), jnp.float32)

    _, flash_vjp = jax.vjp(flash_self_attention, q, k, v)
    _, dense_vjp = jax.vjp(dense_self_attention, q, k, v)
    for got, want, name in zip(flash_vjp(g), dense_vjp(g), "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5,
            err_msg=f"d{name} mismatch",
        )


def test_flash_grad_through_training_loss(rng):
    # End-to-end: grads of a flash-attention LM loss == dense-attention
    # LM loss grads (same params, same batch).
    from distributed_machine_learning_tpu.models.transformer import TransformerLM
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state
    from distributed_machine_learning_tpu.train.losses import lm_cross_entropy

    toks = jnp.asarray(rng.integers(0, 32, (2, 17)), jnp.int32)

    def grads_for(attn):
        model = TransformerLM(vocab_size=32, d_model=16, n_layers=2,
                              n_heads=2, attn_impl=attn)
        state = init_lm_state(model)

        def loss(p):
            return lm_cross_entropy(
                model.apply({"params": p}, toks[:, :-1], train=True),
                toks[:, 1:],
            )

        return jax.grad(loss)(state.params)

    gf = grads_for("flash")
    gd = grads_for("dense")
    for a, b in zip(jax.tree_util.tree_leaves(gf),
                    jax.tree_util.tree_leaves(gd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-6)
