"""Host-side span tracer emitting Chrome trace-event JSON.

``jax.profiler`` (``utils/profiling.py::trace``) already produces the
device-side XPlane trace — MXU occupancy, HBM traffic, collective time.
What it cannot show is the *driver's* phase structure: how long the loop
waited on the data queue, how long host→device placement took, where a
checkpoint save or a supervised restart landed in wall-clock.  This
tracer fills that gap with the complement: cheap host-side spans in the
Chrome trace-event format (`ph:"X"` complete events), loadable in
Perfetto (ui.perfetto.dev) or chrome://tracing, alongside or instead of
the xplane trace.

Crash-safety uses a property of the JSON Array Format: the trailing
``]`` is OPTIONAL for trace viewers, so events are appended as they
complete (``[`` first, then ``,\\n``-separated objects) and a killed
process still leaves a loadable trace.  A clean :meth:`close` terminates
the array, making the file strictly-valid JSON too.

Timestamps are ``perf_counter``-based microseconds (the unit the format
requires), anchored to wall-clock at tracer start so traces appended by
a restarted process stay chronological.  ``pid`` is the JAX process
index, ``tid`` the host thread id — spans from the prefetch thread land
on their own track.
"""

from __future__ import annotations

import json
import os
import threading
import time

from distributed_machine_learning_tpu.telemetry.sink import _rank

# Stop recording past this many events: a month-long run must not grow an
# unbounded trace (the metrics JSONL is the long-horizon artifact).
DEFAULT_MAX_EVENTS = 200_000


class SpanTracer:
    """Appends Chrome trace events to ``path`` as they complete."""

    def __init__(self, path: str | os.PathLike, flush_every: int = 20,
                 enabled: bool | None = None,
                 max_events: int = DEFAULT_MAX_EVENTS):
        if flush_every < 1:
            raise ValueError(f"flush_every must be >= 1, got {flush_every}")
        self.path = os.fspath(path)
        self.flush_every = flush_every
        # None = rank-0 gate, resolved lazily at the first event (see
        # JsonlSink.enabled: construction predates distributed init).
        self._enabled = enabled
        self.max_events = max_events
        self.events_written = 0
        self._file = None
        self._pending = 0
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        # Anchor the (monotonic) perf_counter timeline to wall-clock at
        # tracer start: a re-exec'd process appending to the same trace
        # then lands AFTER the dead run's events instead of overlapping
        # them back at ts≈0.
        self._ts0_us = time.time() * 1e6

    @property
    def enabled(self) -> bool:
        if self._enabled is None:
            self._enabled = _rank() == 0
        return self._enabled

    # -- time ------------------------------------------------------------
    def now(self) -> float:
        """Seconds on the tracer's clock (pass to :meth:`complete`)."""
        return time.perf_counter()

    def _us(self, t_s: float) -> float:
        return (t_s - self._t0) * 1e6 + self._ts0_us

    # -- emission --------------------------------------------------------
    def _emit(self, event: dict) -> None:
        if not self.enabled or self.events_written >= self.max_events:
            return
        with self._lock:
            if self._file is None:
                parent = os.path.dirname(os.path.abspath(self.path))
                os.makedirs(parent, exist_ok=True)
                # Append, not truncate: a supervisor re-exec into the
                # same telemetry dir must extend the timeline, not erase
                # the pre-crash attempts.  A prior run's terminator (or
                # a kill's torn final event) is repaired first so the
                # continued file stays one well-formed array.
                _reopen_trace_array(self.path)
                self._file = open(self.path, "a")
                if self._file.tell() == 0:
                    self._file.write("[\n")
                    first = True
                else:
                    first = False
            else:
                first = False
            if not first:
                self._file.write(",\n")
            self._file.write(json.dumps(event))
            self.events_written += 1
            self._pending += 1
            if self._pending >= self.flush_every:
                self._file.flush()
                os.fsync(self._file.fileno())
                self._pending = 0

    def complete(self, name: str, start_s: float, end_s: float,
                 **args) -> None:
        """Record a completed span [start_s, end_s] (tracer-clock
        seconds, i.e. ``perf_counter`` values)."""
        self._emit({
            "name": name,
            "ph": "X",
            "ts": self._us(start_s),
            "dur": max((end_s - start_s) * 1e6, 0.0),
            "pid": _rank(),
            "tid": threading.get_ident() % 2**31,
            **({"args": args} if args else {}),
        })

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker (``ph:"i"``) — faults, restarts."""
        self._emit({
            "name": name,
            "ph": "i",
            "s": "p",  # process-scoped: draws a flag line across tracks
            "ts": self._us(time.perf_counter()),
            "pid": _rank(),
            "tid": threading.get_ident() % 2**31,
            **({"args": args} if args else {}),
        })

    def span(self, name: str, **args):
        """``with tracer.span("checkpoint_save", step=3): ...`` — records
        the block as a complete event even when it raises (a failed
        restart attempt is exactly the span you want to see)."""
        return _Span(self, name, args)

    # -- lifecycle -------------------------------------------------------
    def flush(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.flush()
                os.fsync(self._file.fileno())
                self._pending = 0

    def close(self) -> None:
        """Terminate the JSON array — the file is then valid strict JSON
        (viewers accepted it even before)."""
        with self._lock:
            if self._file is not None:
                self._file.write("\n]\n")
                self._file.flush()
                self._file.close()
                self._file = None


def _reopen_trace_array(path: str) -> None:
    """Prepare an existing trace file for further appends.

    Two prior-run shapes need repair before ``",\\n{event}"`` can extend
    the array: a CLEAN CLOSE left a trailing ``]`` (appending after it
    would put events outside the array — viewers reject that, unlike a
    merely missing terminator), and a KILL may have left a torn final
    event (appending after it would weld two events into garbage).  The
    terminator is stripped; a torn tail is truncated back to the last
    complete event.  A torn event that happens to end in ``}`` (cut
    inside its args) is indistinguishable from a complete one cheaply —
    ``read_trace`` still skips it as an unparseable chunk.
    """
    try:
        with open(path, "rb+") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            if size == 0:
                return
            back = min(size, 1 << 20)
            f.seek(size - back)
            data = f.read(back)
            end = len(data)

            def rstrip_ws(e: int) -> int:
                while e > 0 and data[e - 1:e] in (b" ", b"\t", b"\r",
                                                  b"\n"):
                    e -= 1
                return e

            end = rstrip_ws(end)
            if end and data[end - 1:end] == b"]":  # clean close: reopen
                end = rstrip_ws(end - 1)
            if end and data[end - 1:end] == b",":  # kill between writes
                end = rstrip_ws(end - 1)
            if end and data[end - 1:end] not in (b"}", b"["):
                # Torn final event: drop back past its separator.
                nl = data.rfind(b"\n", 0, end)
                end = rstrip_ws(nl + 1 if nl >= 0 else 0)
                if end and data[end - 1:end] == b",":
                    end = rstrip_ws(end - 1)
            if end and data[end - 1:end] == b"[":
                end = 0  # nothing but the opener survived: start fresh
            f.truncate(size - len(data) + end)
    except FileNotFoundError:
        return


class _Span:
    __slots__ = ("_tracer", "_name", "_args", "_start")

    def __init__(self, tracer: SpanTracer, name: str, args: dict):
        self._tracer = tracer
        self._name = name
        self._args = args
        self._start = 0.0

    def __enter__(self) -> "_Span":
        self._start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        args = dict(self._args)
        if exc_type is not None:
            args["error"] = exc_type.__name__
        self._tracer.complete(self._name, self._start, time.perf_counter(),
                              **args)


def read_trace(path: str | os.PathLike) -> list[dict]:
    """Load a trace written by :class:`SpanTracer` — closed or not (a
    crash leaves the array unterminated, which viewers and this reader
    both accept; a trailing torn line is dropped the same way
    ``sink.read_jsonl`` drops one)."""
    with open(os.fspath(path)) as f:
        text = f.read()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        pass
    body = text.strip()
    if body.startswith("["):
        body = body[1:]
    body = body.rstrip()
    if body.endswith("]"):
        body = body[:-1]
    events = []
    for chunk in body.split(",\n"):
        chunk = chunk.strip().rstrip(",")
        if not chunk:
            continue
        try:
            events.append(json.loads(chunk))
        except json.JSONDecodeError:
            continue  # torn final event from a mid-write kill
    return events
