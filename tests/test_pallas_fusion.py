"""Round-13 fused-kernel parity gates — interpret mode, fast tier.

Two contracts, two strengths (see the kernel module docstrings):

- **ring codec** (``ops/pallas/ring_codec.py``): BITWISE.  The
  exact-product construction (mantissa-truncated scale) removes the
  FMA-contraction freedom, so the fused build must equal the XLA
  ``WireScheme`` build bit for bit — wire payload, decoded values, EF
  residual, and whole-ring outputs with rank identity — across worlds
  and both topology axes.
- **fused AdamW** (``ops/pallas/fused_adamw.py``): documented ulp
  bound.  Single update from identical state ≤ 8 ulp; fixed-seed
  3-step trajectories compound the last-bit freedom through state (and
  through re-evaluated gradients in the ZeRO-1 keystone), gated at the
  documented relative bound.

Everything here runs the Pallas interpreter on the CPU CI mesh — the
identical kernel code path the TPU compiles — so tier-1 exercises the
fused kernels on every run.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_machine_learning_tpu.ops.ring import (
    Int8Scheme,
    get_wire_scheme,
    ring_all_reduce_flat,
)
from distributed_machine_learning_tpu.runtime.mesh import (
    shard_map_no_check,
)
from distributed_machine_learning_tpu.train.adamw import (
    AdamWConfig,
    adamw_init,
    adamw_update,
)

BATCH_AXIS = "batch"


def _ulps(a, b):
    a = np.asarray(jnp.asarray(a, jnp.float32))
    b = np.asarray(jnp.asarray(b, jnp.float32))
    return int(np.abs(
        a.view(np.int32).astype(np.int64) - b.view(np.int32).astype(np.int64)
    ).max()) if a.size else 0


# ---------------------------------------------------------------------------
# Ring codec: bitwise.
# ---------------------------------------------------------------------------


def _codec_outputs(scheme, v, acc):
    """Every codec seam in one jitted program (the fusion context the
    ring compiles): payload, residual, relay decode, decode-add."""
    L = v.shape[0]

    def f(v, acc):
        enc, err = scheme.encode_with_residual(v)
        return (*enc, err, scheme.decode(enc, L),
                scheme.decode_add(enc, acc, L))

    return jax.jit(f)(v, acc)


@pytest.mark.parametrize("length", [5, 1000, 70000])
def test_codec_seams_bitwise(rng, length):
    v = jnp.asarray(rng.normal(size=length).astype(np.float32))
    acc = jnp.asarray(rng.normal(size=length).astype(np.float32))
    ox = _codec_outputs(Int8Scheme("xla"), v, acc)
    op = _codec_outputs(Int8Scheme("pallas"), v, acc)
    names = ("q", "scale", "residual", "decode", "decode_add")
    for name, a, b in zip(names, ox, op):
        assert a.dtype == b.dtype and a.shape == b.shape, name
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"codec seam {name!r}"
        )


def test_codec_zero_chunk_bitwise():
    v = jnp.zeros(257, jnp.float32)
    for a, b in zip(_codec_outputs(Int8Scheme("xla"), v, v),
                    _codec_outputs(Int8Scheme("pallas"), v, v)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _ring_both(mesh, world, length, scheme, rng):
    g = jnp.asarray(rng.normal(size=(world, length)).astype(np.float32))

    def per_dev(row):
        out, res = ring_all_reduce_flat(
            row[0], BATCH_AXIS, world, mean=True, scheme=scheme,
            return_residual=True,
        )
        return out[None], res[None]

    fn = jax.jit(shard_map_no_check(
        per_dev, mesh=mesh, in_specs=P(BATCH_AXIS),
        out_specs=(P(BATCH_AXIS), P(BATCH_AXIS)),
    ))
    return fn(g)


@pytest.mark.parametrize("world", [2, 4, 8])
def test_ring_codec_bitwise_with_residual(mesh8, world):
    """Whole-ring parity per world: fused == XLA bitwise on the synced
    gradient AND the EF residual, with rank identity preserved (every
    rank ends with identical bits — the replication invariant)."""
    from jax.sharding import Mesh

    mesh = Mesh(np.array(mesh8.devices).reshape(-1)[:world], (BATCH_AXIS,))
    length = 1237
    # One seed, regenerated per run, so both impls see identical bits.
    seed_rng = np.random.default_rng(7)
    ox, rx = _ring_both(mesh, world, length, Int8Scheme("xla"), seed_rng)
    seed_rng = np.random.default_rng(7)
    op, rp = _ring_both(mesh, world, length, Int8Scheme("pallas"), seed_rng)
    np.testing.assert_array_equal(np.asarray(ox), np.asarray(op))
    np.testing.assert_array_equal(np.asarray(rx), np.asarray(rp))
    out = np.asarray(op)
    assert all((out[i] == out[0]).all() for i in range(world)), \
        "rank identity broken: ranks ended with different bits"


@pytest.mark.parametrize("axis", ["inner", "outer"])
def test_hierarchical_codec_bitwise_both_axes(mesh8, axis, rng):
    """The 2x4 hierarchical plan with the int8 codec on EITHER axis:
    fused == XLA bitwise (values + residual), so the knob covers the
    inner reduce-scatter/all-gather hops and the outer sub-ring hops
    alike."""
    from distributed_machine_learning_tpu.ops.topology import (
        Topology,
        topology_all_reduce_flat,
    )

    length = 613
    outs = {}
    for impl in ("xla", "pallas"):
        topo = Topology(2, 4, codec_impl=impl,
                        **{f"{axis}_scheme": "int8"})
        seed_rng = np.random.default_rng(11)
        g = jnp.asarray(
            seed_rng.normal(size=(8, length)).astype(np.float32))

        def per_dev(row, topo=topo):
            out, res = topology_all_reduce_flat(
                row[0], BATCH_AXIS, topo, mean=True, return_residual=True,
                plan="hier",
            )
            return out[None], res[None]

        fn = jax.jit(shard_map_no_check(
            per_dev, mesh=mesh8, in_specs=P(BATCH_AXIS),
            out_specs=(P(BATCH_AXIS), P(BATCH_AXIS)),
        ))
        outs[impl] = fn(g)
    np.testing.assert_array_equal(
        np.asarray(outs["xla"][0]), np.asarray(outs["pallas"][0]))
    np.testing.assert_array_equal(
        np.asarray(outs["xla"][1]), np.asarray(outs["pallas"][1]))


def test_codec_wire_payload_shape_and_accounting():
    """The fused codec must not change the wire: payload leaves keep
    int8[L] + f32[1], and payload_bytes (what the DML103 audit and the
    telemetry counter charge) is impl-independent."""
    for impl in ("xla", "pallas"):
        s = get_wire_scheme("int8", codec_impl=impl)
        q, scale = jax.jit(s.encode)(jnp.ones(300, jnp.float32))
        assert q.dtype == jnp.int8 and q.shape == (300,)
        assert scale.dtype == jnp.float32 and scale.shape == (1,)
        assert s.payload_bytes(300) == 304


def test_codec_non_f32_chunk_falls_back_bitwise(rng):
    """The kernels engage on f32 chunks only (the dtype every ring path
    carries): a bf16 chunk routes the fused seams through the XLA
    arithmetic, so parity holds trivially — the kernel's
    f32-accumulate-round-once would differ in the last bf16 bit."""
    v = jnp.asarray(rng.normal(size=300).astype(np.float32)).astype(
        jnp.bfloat16)
    acc = jnp.asarray(rng.normal(size=300).astype(np.float32)).astype(
        jnp.bfloat16)
    ox = _codec_outputs(Int8Scheme("xla"), v, acc)
    op = _codec_outputs(Int8Scheme("pallas"), v, acc)
    for name, a, b in zip(("q", "scale", "residual", "decode",
                           "decode_add"), ox, op):
        assert a.dtype == b.dtype, name
        np.testing.assert_array_equal(
            np.asarray(jnp.asarray(a, jnp.float32)),
            np.asarray(jnp.asarray(b, jnp.float32)),
            err_msg=f"bf16 codec seam {name!r}",
        )


def test_codec_impl_validation():
    with pytest.raises(ValueError, match="codec impl"):
        get_wire_scheme("int8", codec_impl="triton")
    with pytest.raises(ValueError, match="codec impl"):
        Int8Scheme("triton")
    from distributed_machine_learning_tpu.parallel.strategies import (
        get_strategy,
    )

    with pytest.raises(ValueError, match="codec impl"):
        get_strategy("ring", compress="int8", codec_impl="triton")


# ---------------------------------------------------------------------------
# Fused AdamW: documented ulp bound.
# ---------------------------------------------------------------------------

#: The documented parity bound of ops/pallas/fused_adamw.py: a single
#: update from identical state stays within this many ulp on params
#: and moments (measured worst case 5; zero-moment first steps exact).
SINGLE_UPDATE_ULP = 8
#: 3-step fixed-seed trajectory gate (last-bit freedom compounding
#: through state and re-evaluated gradients; measured 6e-8 on the
#: ZeRO-1 keystone).
TRAJECTORY_REL = 5e-6


def _tree(rng, dtypes=("f32", "f32", "bf16")):
    mk = lambda shape, dt: jnp.asarray(
        rng.normal(size=shape).astype(np.float32)
    ).astype(jnp.bfloat16 if dt == "bf16" else jnp.float32)
    return {"w": mk((37, 19), dtypes[0]), "b": mk((5,), dtypes[1]),
            "e": mk((2000,), dtypes[2])}


def test_fused_adamw_three_fixed_seed_steps(rng):
    """3 fixed-seed updates, fused vs reference trajectories: within
    the documented bound, with the bf16 leaf cast in-kernel."""
    params = _tree(rng)
    cfgs = {False: AdamWConfig(), True: AdamWConfig(fused=True)}
    states = {k: (params, adamw_init(params)) for k in cfgs}
    grads_seq = [
        jax.tree_util.tree_map(
            lambda p: jnp.asarray(
                rng.normal(size=p.shape).astype(np.float32)),
            params,
        )
        for _ in range(3)
    ]
    for step, g in enumerate(grads_seq):
        for fused, cfg in cfgs.items():
            p, m = states[fused]
            states[fused] = jax.jit(
                adamw_update, static_argnames=("config",)
            )(p, m, g, cfg, step=step)
    pr, mr = states[False]
    pf, mf = states[True]
    for k in params:
        assert pf[k].dtype == pr[k].dtype  # bf16 stays bf16
        assert _ulps(pr[k], pf[k]) <= SINGLE_UPDATE_ULP * 3, k
        assert _ulps(mr["mu"][k], mf["mu"][k]) <= SINGLE_UPDATE_ULP * 3, k
        assert _ulps(mr["nu"][k], mf["nu"][k]) <= SINGLE_UPDATE_ULP * 3, k


def test_fused_adamw_single_update_ulp_bound(rng):
    """One update from a WARM (nonzero-moment) shared state — the
    context where FMA contraction has something to perturb — within
    the documented single-update bound."""
    params = _tree(rng, dtypes=("f32", "f32", "f32"))
    moments = adamw_init(params)
    # Warm the moments with one reference step so they are nonzero.
    g0 = jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.normal(size=p.shape).astype(np.float32)),
        params,
    )
    params, moments = adamw_update(params, moments, g0, AdamWConfig(),
                                   step=0)
    g1 = jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.normal(size=p.shape).astype(np.float32)),
        params,
    )
    pr, mr = jax.jit(adamw_update, static_argnames=("config",))(
        params, moments, g1, AdamWConfig(), step=1)
    pf, mf = jax.jit(adamw_update, static_argnames=("config",))(
        params, moments, g1, AdamWConfig(fused=True), step=1)
    for k in params:
        assert _ulps(pr[k], pf[k]) <= SINGLE_UPDATE_ULP, k
        assert _ulps(mr["mu"][k], mf["mu"][k]) <= SINGLE_UPDATE_ULP, k
        assert _ulps(mr["nu"][k], mf["nu"][k]) <= SINGLE_UPDATE_ULP, k


def test_fused_adamw_zero1_keystone(mesh4):
    """The marquee consumer: ZeRO-1 (flat padded vector, one kernel
    launch) over 3 real train steps — fused trajectory within the
    documented relative bound of the reference, and the loss finite."""
    from distributed_machine_learning_tpu.cli.common import (
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.models.vgg import VGGTest
    from distributed_machine_learning_tpu.parallel.zero1 import (
        make_zero1_train_step,
        shard_zero1_state,
    )
    from distributed_machine_learning_tpu.train.step import shard_batch

    model = VGGTest(use_bn=False)
    data_rng = np.random.default_rng(0)
    x = data_rng.integers(0, 256, (16, 32, 32, 3), dtype=np.uint8)
    y = data_rng.integers(0, 10, 16).astype(np.int32)
    flats = {}
    for fused in (False, True):
        st = init_model_and_state(model, config=AdamWConfig(fused=fused))
        z1, unravel, n_elems = shard_zero1_state(st, mesh4)
        step = make_zero1_train_step(model, mesh4, unravel, n_elems,
                                     augment=False, overlap=True)
        xs, ys = shard_batch(mesh4, jnp.asarray(x), jnp.asarray(y))
        for _ in range(3):
            z1, loss = step(z1, xs, ys)
        assert np.isfinite(float(loss))
        flats[fused] = np.asarray(jnp.asarray(z1.param_flat))
    denom = max(float(np.abs(flats[False]).max()), 1e-30)
    rel = float(np.abs(flats[True] - flats[False]).max()) / denom
    assert rel <= TRAJECTORY_REL, rel


# ---------------------------------------------------------------------------
# dmlcheck keeps its teeth through the kernel boundary.
# ---------------------------------------------------------------------------


def test_layer2_sees_through_fused_builds(mesh8):
    """The round-13 acceptance: donation (DML101), critical-path
    (DML102) and wire accounting (DML103) hold THROUGH the pallas_call
    boundary — fused ring step permute-only and fully donated (EF
    residual included), fused zero1 update gather-free with aliased
    moments, kernel build moving the exact same wire bytes — with zero
    new baseline entries."""
    from distributed_machine_learning_tpu.analysis.program_audit import (
        audit_ring_step,
        audit_ring_wire_accounting,
        audit_zero1_step,
    )

    ring = audit_ring_step(mesh8, codec_impl="pallas")
    assert [f.message for f in ring] == []
    zero1 = audit_zero1_step(mesh8, fused_update=True)
    assert [f.message for f in zero1] == []
    findings, table = audit_ring_wire_accounting(
        mesh8, 4096, schemes=("int8",), codec_impl="pallas",
        label="ring_all_reduce_pallas")
    assert [f.message for f in findings] == []
    assert table["int8"]["hlo_bytes"] == table["int8"]["static_bytes"]


def test_callback_walker_descends_pallas_kernels():
    """The jaxpr walker must see INSIDE a pallas_call: a debug_callback
    hidden in a kernel body is the same per-step host round-trip DML104
    exists for."""
    from jax.experimental import pallas as pl

    from distributed_machine_learning_tpu.analysis.program_audit import (
        audit_step_host_callbacks,
    )

    def chatty_kernel(x_ref, o_ref):
        pl.debug_print("x0 = {}", x_ref[0, 0])
        o_ref[...] = x_ref[...] * 2.0

    def step(x):
        return pl.pallas_call(
            chatty_kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            interpret=True,
        )(x)

    x = jax.ShapeDtypeStruct((8, 128), jnp.float32)
    findings = audit_step_host_callbacks(step, x, label="seeded")
    assert findings, "debug print inside a pallas kernel must be flagged"

    def quiet(x):
        from distributed_machine_learning_tpu.ops.pallas.ring_codec import (
            encode_int8,
        )

        return encode_int8(x)

    assert audit_step_host_callbacks(
        quiet, jax.ShapeDtypeStruct((300,), jnp.float32), label="seeded"
    ) == []


# ---------------------------------------------------------------------------
# Deep variants: the kernel benches and the cross-length sweep, slow
# tier with in-test wall-clock caps (the 870s tier-1 budget stays
# protected; `pytest -m ""` runs them).
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_codec_bitwise_deep_sweep(mesh8):
    """Cross-length × cross-world sweep of the bitwise contract,
    capped: the sweep must not eat the slow tier either."""
    t0 = time.monotonic()
    for world in (2, 4, 8):
        from jax.sharding import Mesh

        mesh = Mesh(np.array(mesh8.devices).reshape(-1)[:world],
                    (BATCH_AXIS,))
        for length in (3, 129, 4096, 20011):
            seed_rng = np.random.default_rng(length)
            ox, rx = _ring_both(mesh, world, length, Int8Scheme("xla"),
                                seed_rng)
            seed_rng = np.random.default_rng(length)
            op, rp = _ring_both(mesh, world, length,
                                Int8Scheme("pallas"), seed_rng)
            np.testing.assert_array_equal(np.asarray(ox), np.asarray(op))
            np.testing.assert_array_equal(np.asarray(rx), np.asarray(rp))
    assert time.monotonic() - t0 < 420, "deep sweep blew its wall-clock cap"


@pytest.mark.slow
def test_fused_kernel_bench_smoke():
    """The round-13 bench entrypoints run end to end (tiny config) and
    report the columns PERF.md cites, under a wall-clock cap."""
    from distributed_machine_learning_tpu.bench.fused_kernels import (
        bench_codec_ab,
        bench_update_ab,
    )

    t0 = time.monotonic()
    codec = bench_codec_ab(world=2, iters=3)
    upd = bench_update_ab(world=2, iters=3)
    assert {r["config"] for r in codec} == {"int8_xla", "int8_pallas"}
    assert all(r["loss_bitwise_equal"] for r in codec)
    assert {r["config"] for r in upd} == {"adamw_reference", "adamw_fused"}
    assert all(np.isfinite(r["iter_p50_s"]) for r in codec + upd)
    assert time.monotonic() - t0 < 420, "bench smoke blew its wall-clock cap"
