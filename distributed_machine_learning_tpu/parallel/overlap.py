"""Overlap-aware sharded weight update — shared two-phase machinery.

"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (PAPERS.md, arxiv 2004.13336) shards the optimizer update
across replicas and then has to get the updated-parameter all-gather
OFF the step's critical path — otherwise the sharding trades memory for
a serial collective at the exact point the step produces its output
(the anti-pattern dmlcheck DML102 flags: a sync all-gather feeding the
ROOT tuple).  The overlap recipe ("Massively Distributed SGD", arxiv
1811.05233: hide parameter movement under work that does not need the
fresh parameters) splits every flat-shard scheme's step into:

- an **update phase**: forward/backward, gradient reduce-scatter, and
  the shard-local optimizer step — a program that ends at the updated
  SHARD.  The host's ``block_until_ready(loss)`` returns as soon as
  this program lands; no gather is inside it.
- a **consume phase**: the gather of the updated shards back to the
  replicated full vector, dispatched immediately as its OWN program —
  a bucketed :func:`~distributed_machine_learning_tpu.ops.ring.ring_all_gather_flat`
  ppermute chain (bucket k's DMA hides bucket k±1's assembly; verified
  in the v5e AOT schedule).  Dispatch is async, so the gather executes
  behind the host's ``data_wait``/``place_batch`` for the next batch
  and its result is consumed by the next step's forward.

Both phases are pure data-movement refactorings of the sync step —
the overlapped trajectory is BIT-IDENTICAL to the sync one (tested for
zero1 and fsdp on the 8-device mesh).

This module owns the pieces zero1 and fsdp share, so the two overlap
protocols cannot drift apart: the jitted ring-gather program builder
and the ``param_gather`` telemetry bookkeeping (span from gather
dispatch to observed readiness, closed at the next step's consume;
``pop_gather_seconds()`` feeds the train loop's ``param_gather_s`` row
column — the span that should overlap ``data_wait`` on the trace
timeline while ``device_block`` shrinks).
"""

from __future__ import annotations

import time

import jax
from jax.sharding import PartitionSpec as P

from distributed_machine_learning_tpu.runtime.mesh import (
    shard_map_no_check as _shard_map,
)

# Buckets for the consume-phase ring gather: enough to keep several
# DMAs in flight with the other buckets' assembly under them (the v5e
# schedule audit shows 4 concurrent DMAs at 4 buckets), few enough that
# per-hop payloads stay fat.
DEFAULT_GATHER_BUCKETS = 4


def make_ring_gather(mesh, axis_name: str, axis_size: int,
                     n_buckets: int = DEFAULT_GATHER_BUCKETS,
                     donate: bool = True):
    """The consume-phase program: jitted shard_map'd bucketed ring
    all-gather, ``[padded] P(axis)`` shards → ``[padded] P()``
    replicated.  ``donate=True`` lets the shard buffers die into the
    gather (zero1: nothing else reads them); fsdp keeps them alive
    (``donate=False`` — the shards ARE the state)."""
    from distributed_machine_learning_tpu.ops.ring import (
        ring_all_gather_flat,
    )

    def _gather(shards):
        return ring_all_gather_flat(shards, axis_name, axis_size,
                                    n_buckets=n_buckets)

    fn = _shard_map(_gather, mesh=mesh,
                    in_specs=P(axis_name), out_specs=P())
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


class GatherSpanClock:
    """Host-side bookkeeping for the in-flight consume-phase gather.

    ``open(value)`` notes dispatch time; ``close()`` — called at the
    next step's consume — blocks on the value and records the
    ``param_gather`` trace span (dispatch → observed ready).  The block
    only happens when telemetry is installed: the telemetry-off path
    never adds a host sync (the next update program would wait on its
    input anyway).  ``pop()`` hands the last closed duration to the
    train loop exactly once (the ``param_gather_s`` row column)."""

    def __init__(self):
        self._t0 = None
        self._value = None
        self._last_s = None

    def open(self, value):
        self._t0, self._value = time.perf_counter(), value

    def close(self):
        from distributed_machine_learning_tpu.telemetry import get_telemetry

        tel = get_telemetry()
        if tel is None or self._t0 is None:
            self._t0 = self._value = None
            return
        jax.block_until_ready(self._value)
        t1 = time.perf_counter()
        tel.tracer.complete("param_gather", self._t0, t1)
        self._last_s = t1 - self._t0
        self._t0 = self._value = None

    def pop(self):
        v, self._last_s = self._last_s, None
        return v
