"""Text generation entrypoint — serve a checkpoint trained by ``cli.lm``.

The reference has no inference surface at all (SURVEY.md §2 — its
``test_model`` is classification eval); this CLI completes the LM
serving loop the framework adds: restore a ``cli.lm --ckpt-dir``
checkpoint, encode the prompt with the same byte-level scheme the
trainer's ``--data-dir`` corpora use (``data/text.py``: vocab 256 bytes
+ BOS), and run the KV-cached jitted generate loop
(``inference/generate.py`` — flash prefill, GQA-native narrow-cache
decode).

Usage::

    python -m distributed_machine_learning_tpu.cli.generate \
        --ckpt-dir runs/lm --prompt "The " --max-new-tokens 128 \
        --d-model 256 --n-layers 4 --n-heads 8   # match the training run

Model flags must match the training run (the checkpoint stores arrays,
not architecture).  Pipeline-layout checkpoints (``--parallel pp/3d``)
are detected by their stacked ``blocks`` tree and unstacked
automatically.  ``--random-init`` serves an untrained model (demo /
smoke path — no checkpoint needed).
"""

from __future__ import annotations

import argparse

import numpy as np


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--ckpt-dir", default=None,
                   help="directory written by cli.lm --ckpt-dir")
    p.add_argument("--random-init", action="store_true",
                   help="serve freshly initialized weights (no checkpoint)")
    p.add_argument("--prompt", default="The ")
    p.add_argument("--max-new-tokens", dest="max_new_tokens", default=128,
                   type=int)
    p.add_argument("--temperature", default=1.0, type=float,
                   help="0 = greedy decoding")
    p.add_argument("--top-k", dest="top_k", default=None, type=int)
    p.add_argument("--top-p", dest="top_p", default=None, type=float,
                   help="nucleus sampling: keep the smallest token set "
                        "whose TEMPERED cumulative probability >= p "
                        "(HF warper order: temperature, then top-k, "
                        "then top-p)")
    p.add_argument("--seed", default=0, type=int)
    # Architecture flags — must match the training run.
    p.add_argument("--d-model", dest="d_model", default=256, type=int)
    p.add_argument("--n-layers", dest="n_layers", default=4, type=int)
    p.add_argument("--n-heads", dest="n_heads", default=8, type=int)
    p.add_argument("--n-kv-heads", dest="n_kv_heads", default=None, type=int)
    p.add_argument("--moe", action="store_true",
                   help="serve a Switch-MoE checkpoint (cli.lm --parallel "
                        "ep): per-token routing runs inside the cached "
                        "decode loop; pair with --n-experts etc.")
    p.add_argument("--n-experts", dest="n_experts", default=8, type=int)
    p.add_argument("--capacity-factor", dest="capacity_factor",
                   default=1.25, type=float)
    p.add_argument("--moe-impl", dest="moe_impl", default="einsum",
                   choices=["einsum", "grouped"])
    p.add_argument("--vocab", default=None, type=int,
                   help="default: byte-level 257 (data/text.py)")
    p.add_argument("--compute-dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    p.add_argument("--kv-cache-dtype", dest="kv_cache_dtype", default=None,
                   help="decode cache storage dtype (default: compute "
                        "dtype)")
    p.add_argument("--quant", default=None, choices=["int8"],
                   help="weight-only quantized serving: projections read "
                        "int8 weights through the Pallas kernel "
                        "(ops/quant.py) — decode is weight-bandwidth-"
                        "bound, measured 1.3-1.8x tokens/s (docs/PERF.md)")
    p.add_argument("--tp", default=1, type=int,
                   help="tensor-parallel decode over this many devices "
                        "(manual Megatron shard_map — heads, d_ff, and "
                        "the KV cache sharded; composes with --quant "
                        "int8: inference/generate.py::make_tp_generate_fn)")
    # Speculative decoding (inference/speculative.py): a cheap draft
    # model proposes --spec-gamma tokens per target verify pass; output
    # distribution is EXACTLY the target's (greedy: bitwise-identical).
    p.add_argument("--spec-gamma", dest="spec_gamma", default=0, type=int,
                   help="enable speculative decoding with this many draft "
                        "tokens per verify round (0 = off); the draft "
                        "defaults to the target architecture at random "
                        "init unless --draft-* flags say otherwise; "
                        "composes with --quant, --moe, and --tp (the "
                        "target verifies sharded, the draft replicates)")
    p.add_argument("--draft-ckpt-dir", dest="draft_ckpt_dir", default=None,
                   help="cli.lm checkpoint for the draft model; absent "
                        "= random-init draft (output stays exact, "
                        "acceptance is just poor)")
    p.add_argument("--draft-d-model", dest="draft_d_model", default=None,
                   type=int, help="draft architecture (defaults mirror "
                                  "the target's flags)")
    p.add_argument("--draft-n-layers", dest="draft_n_layers", default=None,
                   type=int)
    p.add_argument("--draft-n-heads", dest="draft_n_heads", default=None,
                   type=int)
    p.add_argument("--draft-n-kv-heads", dest="draft_n_kv_heads",
                   default=None, type=int)
    return p


def _restore_lm_params(ckpt_dir: str, n_layers: int):
    """Restore a cli.lm checkpoint's params, unstacking pipeline-layout
    trees (contiguous or interleaved) into the per-layer form plain
    apply expects — the ONE restore path for target AND draft models."""
    from distributed_machine_learning_tpu.train.checkpoint import (
        checkpoint_layout,
        latest_checkpoint,
        restore_checkpoint,
    )

    latest = latest_checkpoint(ckpt_dir)
    if latest is None:
        raise FileNotFoundError(f"no complete checkpoint under {ckpt_dir}")
    params = restore_checkpoint(latest, files_verified=True).params
    if "blocks" in params:
        from distributed_machine_learning_tpu.parallel.pipeline_interleaved import (  # noqa: E501
            parse_interleaved_layout,
        )

        interleaved = parse_interleaved_layout(checkpoint_layout(latest))
        if interleaved is not None:
            from distributed_machine_learning_tpu.parallel.pipeline_interleaved import (  # noqa: E501
                unstack_interleaved,
            )

            p_saved, v_saved = interleaved
            params = unstack_interleaved(params, n_layers, p_saved, v_saved)
        else:
            from distributed_machine_learning_tpu.parallel.pipeline import (
                unstack_lm_params,
            )

            params = unstack_lm_params(params, n_layers)
    print(f"restored {latest}")
    return params


def main(argv=None) -> None:
    args = make_parser().parse_args(argv)
    if not args.ckpt_dir and not args.random_init:
        raise ValueError("pass --ckpt-dir (a cli.lm checkpoint) or "
                         "--random-init")

    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.data.text import BOS, VOCAB_SIZE
    from distributed_machine_learning_tpu.inference.generate import (
        make_generate_fn,
    )
    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )

    vocab = args.vocab or VOCAB_SIZE
    dtype = (jnp.bfloat16 if args.compute_dtype == "bfloat16"
             else jnp.float32)
    kv_dtype = (
        jnp.dtype(args.kv_cache_dtype) if args.kv_cache_dtype else None
    )
    if args.moe:
        from distributed_machine_learning_tpu.models.moe import (
            MoETransformerLM,
        )

        model = MoETransformerLM(
            vocab_size=vocab, d_model=args.d_model,
            n_layers=args.n_layers, n_heads=args.n_heads,
            n_kv_heads=args.n_kv_heads, n_experts=args.n_experts,
            capacity_factor=args.capacity_factor, moe_impl=args.moe_impl,
            compute_dtype=dtype, kv_cache_dtype=kv_dtype,
        )
    else:
        model = TransformerLM(
            vocab_size=vocab,
            d_model=args.d_model,
            n_layers=args.n_layers,
            n_heads=args.n_heads,
            n_kv_heads=args.n_kv_heads,
            compute_dtype=dtype,
            kv_cache_dtype=kv_dtype,
        )

    if args.ckpt_dir:
        params = _restore_lm_params(args.ckpt_dir, args.n_layers)
    else:
        from distributed_machine_learning_tpu.train.lm_step import (
            init_lm_state,
        )

        params = init_lm_state(model).params
        print("WARNING: --random-init weights (untrained output)")
    # Serving configuration: quantize (from the fp32 master params) or
    # cast to the compute dtype (decode is bound by HBM weight reads).
    if args.quant == "int8":
        from distributed_machine_learning_tpu.ops.quant import (
            quantize_lm_params,
        )

        params = quantize_lm_params(params)
    else:
        params = jax.tree_util.tree_map(
            lambda p: p.astype(dtype) if p.dtype == jnp.float32 else p,
            params,
        )

    # Byte-level prompt encoding, BOS-prefixed like every corpus
    # document (data/text.py::load_corpus).
    prompt_bytes = args.prompt.encode("utf-8")
    if vocab == VOCAB_SIZE:
        toks = [BOS] + list(prompt_bytes)
    else:
        toks = [b % vocab for b in prompt_bytes] or [0]
    prompt = jnp.asarray(np.asarray(toks, np.int32)[None, :])

    # Shared TP setup (one copy for the speculative and plain branches):
    # device-count guard + the model-axis mesh.  The Megatron param
    # arrangement (tp_decode_params) runs AFTER the factory below — the
    # factories' divisibility validation (tp_local_decode_clone) must
    # fire before any reshape touches the arrays.
    mesh = None
    if args.tp > 1:
        from distributed_machine_learning_tpu.runtime.mesh import make_mesh

        if args.tp > jax.device_count():
            raise ValueError(
                f"--tp {args.tp} exceeds the device count "
                f"{jax.device_count()} (the mesh uses the first tp "
                "devices)"
            )
        mesh = make_mesh(args.tp, axis_names=("model",))

    if args.spec_gamma > 0:
        from distributed_machine_learning_tpu.inference.speculative import (
            make_speculative_generate_fn,
            make_tp_speculative_generate_fn,
        )

        # The draft is a plain dense LM even for an MoE target — it only
        # proposes; the target's verify pass owns the distribution.  It
        # shares --kv-cache-dtype: the draft runs the most decode steps,
        # so the int8 cache pays off there first (ADVICE r4).
        draft = TransformerLM(
            vocab_size=vocab,
            d_model=args.draft_d_model or args.d_model,
            n_layers=args.draft_n_layers or args.n_layers,
            n_heads=args.draft_n_heads or args.n_heads,
            n_kv_heads=(args.draft_n_kv_heads
                        if args.draft_n_kv_heads is not None
                        else args.n_kv_heads),
            compute_dtype=dtype,
            kv_cache_dtype=kv_dtype,
        )
        from distributed_machine_learning_tpu.train.lm_step import (
            init_lm_state,
        )

        if args.draft_ckpt_dir:
            draft_params = _restore_lm_params(
                args.draft_ckpt_dir, draft.n_layers
            )
        else:
            draft_params = init_lm_state(draft, seed=11).params
            print("WARNING: random-init draft (exact output, poor "
                  "acceptance)")
        draft_params = jax.tree_util.tree_map(
            lambda p: p.astype(dtype) if p.dtype == jnp.float32 else p,
            draft_params,
        )
        if mesh is not None:
            spec_fn = make_tp_speculative_generate_fn(
                model, draft, args.max_new_tokens, mesh,
                gamma=args.spec_gamma, temperature=args.temperature,
                top_k=args.top_k, top_p=args.top_p, quantize=args.quant,
            )
        else:
            spec_fn = make_speculative_generate_fn(
                model, draft, args.max_new_tokens, gamma=args.spec_gamma,
                temperature=args.temperature, top_k=args.top_k,
                top_p=args.top_p, quantize=args.quant,
            )
        # Same (params, prompt, key) signature as the other paths, so
        # the shared detokenize/print epilogue below serves all three.
        fn = lambda p, pr, k: spec_fn(p, draft_params, pr, k)
    elif mesh is not None:
        from distributed_machine_learning_tpu.inference.generate import (
            make_tp_generate_fn,
        )

        fn = make_tp_generate_fn(
            model, args.max_new_tokens, mesh,
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, quantize=args.quant,
        )
    else:
        fn = make_generate_fn(model, args.max_new_tokens,
                              temperature=args.temperature,
                              top_k=args.top_k, top_p=args.top_p,
                              quantize=args.quant)
    if mesh is not None:
        from distributed_machine_learning_tpu.parallel.tensor_parallel import (  # noqa: E501
            tp_decode_params,
        )

        params = tp_decode_params(params, args.tp)
    out = np.asarray(
        fn(params, prompt, jax.random.PRNGKey(args.seed))
    )[0, prompt.shape[1]:]
    if vocab == VOCAB_SIZE:
        text = bytes(t for t in out.tolist() if t < 256).decode(
            "utf-8", errors="replace"
        )
    else:
        text = " ".join(str(t) for t in out.tolist())
    print(args.prompt + text)


if __name__ == "__main__":
    main()
