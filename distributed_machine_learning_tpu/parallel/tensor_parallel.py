"""Tensor parallelism for the transformer — the GSPMD way.

Capability beyond the reference (TP absent — SURVEY.md §2.3), designed
TPU-first: instead of hand-writing Megatron's f/g collectives, we declare
*where parameters live* (column-split then row-split per block, the
Megatron layout) as ``PartitionSpec`` rules and ``jit`` the unmodified
train step with those in/out shardings.  XLA's SPMD partitioner then
derives every activation sharding and inserts the all-reduces — one psum
after attention-out and one after fc_out per block, riding ICI — which is
exactly Megatron's schedule, obtained from the compiler instead of
hand-rolled comm calls.

Composes with data parallelism on the same mesh: batch sharded over
``data_axis``, params over ``model_axis``; the compiler emits the gradient
all-reduce over ``data_axis`` and the activation all-reduces over
``model_axis`` in one program it can overlap freely.

Layout rules (flax param paths of ``models/transformer.py``):

  ====================  =====================  ========================
  param                 shape                  spec (model axis = "model")
  ====================  =====================  ========================
  attn qkv kernel       [E, 3, H, Dh]          heads sharded: (·,·,model,·)
  attn qkv bias         [3, H, Dh]             (·,model,·)
  attn out kernel       [H, Dh, E]             row-split: (model,·,·)
  fc_in kernel          [E, F]                 column-split: (·,model)
  fc_in bias            [F]                    (model,)
  fc_out kernel         [F, E]                 row-split: (model,·)
  embed embedding       [V, E]                 vocab-sharded: (model,·)
  lm_head kernel        [E, V]                 column-split: (·,model)
  lm_head bias          [V]                    (model,)
  everything else       —                      replicated
  ====================  =====================  ========================
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_machine_learning_tpu.parallel.gspmd import (
    make_cached_sharded_step,
    shard_state,
    state_shardings,
)
from distributed_machine_learning_tpu.train.lm_step import _lm_step_impl
from distributed_machine_learning_tpu.train.state import TrainState

MODEL_AXIS = "model"


def tp_spec_for(path: tuple[str, ...], ndim: int, model_axis: str = MODEL_AXIS) -> P:
    """PartitionSpec for one parameter, by its flax path."""
    path = tuple(path)
    leaf = path[-1]
    module = path[-2] if len(path) >= 2 else ""
    m = model_axis
    if module == "qkv":
        return P(None, None, m, None) if leaf == "kernel" else P(None, m, None)
    if module == "q":
        # GQA query projection: kernel [E, H, Dh], bias [H, Dh].
        return P(None, m, None) if leaf == "kernel" else P(m, None)
    if module == "kv":
        # GQA K/V projection: kernel [E, 2, Hkv, Dh], bias [2, Hkv, Dh].
        return P(None, None, m, None) if leaf == "kernel" else P(None, m, None)
    if module == "out" and leaf == "kernel":
        return P(m, None, None)
    if module == "fc_in":
        return P(None, m) if leaf == "kernel" else P(m)
    if module == "fc_out" and leaf == "kernel":
        return P(m, None)
    if module == "embed" and leaf == "embedding":
        return P(m, None)
    if module == "lm_head":
        return P(None, m) if leaf == "kernel" else P(m)
    return P(*(None,) * ndim)


def _spec_for(model_axis: str):
    # gspmd.SpecFor passes the leaf shape; the TP rules only need rank.
    return lambda path, shape: tp_spec_for(path, len(shape), model_axis)


def tp_state_shardings(
    state: TrainState, mesh: Mesh, model_axis: str = MODEL_AXIS
):
    """NamedSharding pytree for a TrainState: params + momentum follow the
    TP layout, scalar fields replicate."""
    return state_shardings(state, mesh, _spec_for(model_axis))


def shard_tp_state(
    state: TrainState, mesh: Mesh, model_axis: str = MODEL_AXIS
) -> TrainState:
    """Place a (host or replicated) TrainState into the TP layout."""
    return shard_state(state, mesh, _spec_for(model_axis))


def make_tp_lm_train_step(
    model,
    mesh: Mesh,
    data_axis: str = "batch",
    model_axis: str = MODEL_AXIS,
):
    """Build the TP(+DP) LM train step.

    ``model`` may use dense, flash, or auto attention (flash runs
    head-sharded inside the model's fully-manual shard_map wrap — see
    ``Attention.flash_head_axis``; sequence stays whole — combining TP
    with ring attention is the 3-D mesh step's job).
    The returned ``step(state, tokens, targets)`` expects ``state`` already
    placed via ``shard_tp_state`` and tokens/targets sharded over
    ``data_axis`` (see ``shard_tp_batch``).

    The sharding declarations are built from the first call's actual state
    (and cached per tree structure), so custom SGDConfig values — static
    pytree metadata on TrainState — never mismatch the jitted signature.
    """
    for a in (data_axis, model_axis):
        if a not in mesh.axis_names:
            raise ValueError(f"mesh is missing axis {a!r}: {mesh.axis_names}")
    if model.attn_impl in ("flash", "auto") and model.flash_mesh is None:
        # Flash composes with TP through the model's fully-manual
        # shard_map wrap with the HEAD dim sharded over the model axis:
        # heads are independent in flash, and each shard's local GQA
        # grouping stays aligned because H_local = groups · Hkv_local
        # (the divisibility checks below enforce both).  The Mosaic
        # custom call then sees local head counts and never meets the
        # partitioner.
        model = model.clone(
            flash_mesh=mesh,
            flash_batch_axis=data_axis,
            flash_head_axis=model_axis,
        )
    elif model.attn_impl not in ("dense", "flash", "auto"):
        raise ValueError(
            "tensor-parallel step supports dense/flash/auto attention; "
            "ring attention composes with TP via the 3-D mesh step"
        )
    n_model = mesh.shape[model_axis]
    if model.n_heads % n_model:
        raise ValueError(
            f"n_heads={model.n_heads} must be divisible by the model-axis "
            f"size {n_model} (heads are sharded over {model_axis!r})"
        )
    n_kv = getattr(model, "n_kv_heads", None)
    if n_kv is not None and n_kv % n_model:
        raise ValueError(
            f"n_kv_heads={n_kv} must be divisible by the model-axis size "
            f"{n_model} (K/V heads are sharded over {model_axis!r})"
        )
    batch_sharding = NamedSharding(mesh, P(data_axis, None))
    impl = partial(_lm_step_impl, model, axis_names=())
    return make_cached_sharded_step(impl, mesh, _spec_for(model_axis), batch_sharding)


def shard_tp_batch(mesh: Mesh, tokens, targets, data_axis: str = "batch"):
    """Tokens/targets sharded over the data axis, sequence whole."""
    from distributed_machine_learning_tpu.train.lm_step import shard_lm_batch

    return shard_lm_batch(mesh, tokens, targets, data_axis=data_axis, seq_axis=None)
