"""Shared Pallas plumbing: one interpret-mode knob for every kernel.

Every kernel module (``flash_attention``, ``quant_matmul``,
``ring_codec``, ``fused_adamw``, ``decode_attention``, ...) needs the
same two decisions made the same way:

- ``interpret()`` — whether ``pl.pallas_call`` should run the kernel
  under the Pallas interpreter instead of Mosaic.  Mosaic only compiles
  for TPU, so any non-TPU backend (the 8-virtual-device CPU CI mesh,
  the multi-chip dryrun's virtual CPU devices) interprets; a TPU
  backend compiles.  Historically this predicate lived in
  ``flash_attention._interpret`` and was imported sideways by
  ``quant_matmul`` — it is hoisted here so interpret-mode selection is
  ONE knob for all kernels (the old import path is kept as an alias).
- ``HAS_PLTPU`` / ``pltpu`` — the ``jax.experimental.pallas.tpu``
  import, which only resolves fully on TPU-capable installs; kernels
  gate their ``CompilerParams``/memory-space usage on it.

``pick_block`` is the shared tiling helper (grown in ``quant_matmul``):
the largest multiple-of-``quantum`` divisor of a dimension under a VMEM
target.
"""

from __future__ import annotations

import jax

try:  # pltpu imports only resolve fully on TPU-capable installs
    from jax.experimental.pallas import tpu as pltpu

    HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    HAS_PLTPU = False


def interpret() -> bool:
    """True when Pallas kernels must run interpreted (non-TPU backend).

    An explicitly configured default device wins: a process whose
    highest-priority backend is a TPU can still route computations to
    virtual CPU devices (the multi-chip dryrun does exactly that), and
    Mosaic can't compile for CPU — interpret there.  The config also
    accepts plain strings ("cpu", "tpu:0"), so parse those too.
    """
    dev = jax.config.jax_default_device
    if dev is not None:
        platform = (
            dev.platform
            if hasattr(dev, "platform")
            else str(dev).split(":")[0]
        )
        return platform != "tpu"
    return jax.default_backend() != "tpu"


# Alias under the historical private name (flash_attention grew the
# predicate; quant_matmul imported it from there) so both spellings
# resolve to the one definition above.
_interpret = interpret
_HAS_PLTPU = HAS_PLTPU


#: VMEM lane width — the last dim of every kernel tile.
LANES = 128


def padded_lane_rows(length: int, row_quantum: int) -> int:
    """Rows of a ``[rows, LANES]`` view of a flat ``[length]`` vector,
    padded up to ``row_quantum`` (the dtype's sublane tile quantum:
    8 for f32, 16 for bf16, 32 for int8)."""
    lane_rows = -(-max(length, 1) // LANES)
    return -(-lane_rows // row_quantum) * row_quantum


def lane_tiles(a, rows: int, dtype=None):
    """Flat ``[L]`` → zero-padded ``[rows, LANES]`` (optionally cast
    first).  Zero pads are the exact-by-construction convention every
    elementwise kernel here relies on: padded lanes quantize/decode/
    update to exactly zero and are sliced off by the caller."""
    import jax.numpy as jnp

    if dtype is not None:
        a = a.astype(dtype)
    return jnp.pad(a, (0, rows * LANES - a.shape[0])).reshape(rows, LANES)


def tile_compiler_params(semantics) -> dict:
    """``{"compiler_params": pltpu.CompilerParams(...)}`` when Mosaic
    will compile the kernel, ``{}`` under the interpreter (which
    rejects TPU compiler params) — the gate every kernel call spells
    around its ``dimension_semantics``."""
    if HAS_PLTPU and not interpret():
        return {"compiler_params": pltpu.CompilerParams(
            dimension_semantics=tuple(semantics))}
    return {}


def pick_block(n: int, target: int, quantum: int) -> int | None:
    """Largest multiple-of-``quantum`` divisor of n that is <= target,
    or n itself when n < quantum (Mosaic accepts a block equal to the
    full array dim)."""
    if n <= quantum:
        return n
    best = None
    b = quantum
    while b <= min(n, target):
        if n % b == 0:
            best = b
        b += quantum
    return best if best is not None else (n if n <= target else None)
