"""Sort-based grouped expert MLP — the dropless MoE compute path.

The einsum dispatch in ``models/moe.py`` is the right shape for GSPMD
expert parallelism (the one-hot dispatch/combine einsums are what the
partitioner turns into the token all-to-all), but on a single device it
pays O(N·E·C·D) = O(1.25·N²·D) FLOPs of pure data movement per
dispatch/combine pair — quadratic in tokens and all of it off the MXU's
useful-work path.  The grouped path here is the TPU-idiomatic
alternative (the design MegaBlocks argues for on GPUs, mapped onto
XLA's native ragged matmul): sort token rows by their routed expert,
run one ``lax.ragged_dot`` per projection over the contiguous groups,
and unsort.  Dispatch cost falls to O(N·D) gather/scatter bandwidth,
and the expert matmuls run at dense-matmul MFU (measured on this
repo's chip: 134 TF/s ragged vs 94 TF/s effective for the einsum
fragment at N=8k, D=2k, F=8k — before counting the combine einsum).

It is also **dropless**: every token reaches its expert, with no
capacity rounding — group sizes are data-dependent *values*, which
``ragged_dot`` consumes without shape dynamism (output shape stays
[N, F]).  Capacity/overflow semantics (Switch's) remain available via
the einsum path; parity between the two holds whenever capacity is
ample enough that nothing drops (tested).

Scope: single-device and shard_map-style data parallelism (each device
runs this on its local tokens).  The GSPMD expert-sharded step keeps
the einsum path — ``ragged_dot`` has no partitioning rule that would
recover the all-to-all (guarded in ``parallel/expert_parallel.py``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def sort_by_expert(expert_idx: jax.Array, n_experts: int):
    """Permutation that groups token rows by expert, plus group sizes.

    Returns ``(order, inv_order, group_sizes)``: ``order`` sorts rows so
    expert 0's tokens come first, ``inv_order`` undoes it, and
    ``group_sizes[e]`` counts expert e's tokens (int32, as
    ``lax.ragged_dot`` requires).

    Counting sort, not ``argsort``: a bitonic sort of N int keys costs
    ~log²N full-array passes on the VPU (measured ~2 ms at N=8k on this
    chip — comparable to one of the expert matmuls it feeds).  With E
    experts the permutation is cheaper to *construct*: one [N, E] cumsum
    over the routing one-hot gives each token its rank within its
    expert's group, an exclusive-sum of group sizes gives each group's
    base offset, and rank + offset IS the token's destination slot —
    stable, total, and O(N·E) elementwise work.
    """
    n = expert_idx.shape[0]
    onehot = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.int32)  # [N, E]
    ranks = jnp.cumsum(onehot, axis=0)  # rank-within-expert, 1-based at own row
    group_sizes = ranks[-1]  # [E] — totals; int32 already
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(group_sizes)[:-1]]
    )  # exclusive prefix: group e starts at offsets[e]
    # Destination slot of each token = its group's base + its 0-based rank.
    dest = offsets[expert_idx] + (
        jnp.sum(ranks * onehot, axis=1, dtype=jnp.int32) - 1
    )
    inv_order = dest  # sorted[dest[i]] = tokens[i]  ⇒  dest inverts order
    order = jnp.zeros((n,), jnp.int32).at[dest].set(
        jnp.arange(n, dtype=jnp.int32)
    )
    return order, inv_order, group_sizes


@jax.custom_vjp
def _permute_rows(x: jax.Array, perm: jax.Array, inv_perm: jax.Array):
    """``x[perm]`` with a permutation-aware VJP.

    ``jnp.take``'s generic transpose is a scatter-add (indices could
    repeat), which TPUs execute row-at-a-time — profiled at ~22 GB/s on
    this chip, ~3 ms per [8k, 2k] un-permute in the MoE backward.  A
    permutation is bijective, so its cotangent is just the gather by the
    inverse permutation: both directions run at gather (HBM) speed.
    """
    return jnp.take(x, perm, axis=0)


def _permute_rows_fwd(x, perm, inv_perm):
    return jnp.take(x, perm, axis=0), (perm, inv_perm)


def _permute_rows_bwd(res, ct):
    perm, inv_perm = res
    return jnp.take(ct, inv_perm, axis=0), None, None


_permute_rows.defvjp(_permute_rows_fwd, _permute_rows_bwd)


def grouped_expert_mlp(
    tokens: jax.Array,
    expert_idx: jax.Array,
    w_in: jax.Array,
    b_in: jax.Array,
    w_out: jax.Array,
    b_out: jax.Array,
    *,
    activation=jax.nn.gelu,
) -> jax.Array:
    """Dropless routed expert MLP over ``[N, D]`` token rows.

    ``tokens``: [N, D] (already cast to the compute dtype);
    ``expert_idx``: [N] int routed expert per token; weights carry the
    leading [E, ...] expert axis.  Returns [N, D] in ``tokens.dtype`` —
    the caller applies router-prob scaling.  Gradients flow to tokens
    and all four weight leaves through ``ragged_dot``'s VJP; the integer
    routing path is non-differentiable exactly as the one-hot path is.
    """
    n_experts = w_in.shape[0]
    order, inv_order, group_sizes = sort_by_expert(expert_idx, n_experts)
    xs = _permute_rows(tokens, order, inv_order)
    eids = jnp.take(expert_idx, order, axis=0)
    dt = tokens.dtype
    h = lax.ragged_dot(xs, w_in.astype(dt), group_sizes)
    h = activation(h + jnp.take(b_in.astype(dt), eids, axis=0))
    ys = lax.ragged_dot(h, w_out.astype(dt), group_sizes)
    ys = ys + jnp.take(b_out.astype(dt), eids, axis=0)
    return _permute_rows(ys, inv_order, order)
