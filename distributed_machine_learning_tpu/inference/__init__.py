from distributed_machine_learning_tpu.inference.generate import (
    generate,
    make_generate_fn,
    make_tp_generate_fn,
)
from distributed_machine_learning_tpu.inference.speculative import (
    make_speculative_generate_fn,
    make_tp_speculative_generate_fn,
)

__all__ = [
    "generate",
    "make_generate_fn",
    "make_tp_generate_fn",
    "make_speculative_generate_fn",
    "make_tp_speculative_generate_fn",
]
