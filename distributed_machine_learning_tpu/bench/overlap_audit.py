"""Ring-bucket comm/compute overlap audit — schedule-level proof.

The north-star program (``ops/ring.py``) claims XLA's async collective
scheduler overlaps bucket k's ppermutes with bucket k+1's adds — the
property DDP's C++ reducer provides and the reason 25 MB buckets exist
(``/root/reference/part3/main.py:59``, group25.pdf p.6).  A single
attached chip cannot *run* an 8-device ring (a 1-device mesh has zero
ppermutes), so this audit produces the strongest evidence available
without a pod: it AOT-compiles the full part3 train step for a REAL
multi-chip TPU target (``jax.experimental.topologies`` — the same
XLA:TPU backend, latency-hiding scheduler included, that a pod would
use) and walks the optimized module's schedule:

- every ``collective-permute-start``/``-done`` pair is an async window
  in which the DMA is in flight;
- compute ops textually scheduled between start and done execute under
  that DMA — the overlap, read straight off the executable.

Run: ``python -m distributed_machine_learning_tpu.bench.overlap_audit``
(needs libtpu for the compile-only TPU client; prints one JSON line).

This is a static schedule, not a device timeline: it proves the
executable *orders* bucket math under bucket DMAs, while actual wall-
clock hiding additionally depends on DMA latency vs fusion runtime —
the part a pod xprof would add.

**Wire-byte audit** (round 7, ``--wire-bytes``): the compressed ring
(``ops/ring.py`` wire schemes) claims ~4x fewer bytes per hop for the
int8 codec.  :func:`wire_bytes_from_hlo` reads the claim off the
COMPILED program — it sums the operand bytes of every
``collective-permute``/``collective-permute-start`` the executable
actually issues — so the reduction is verified in the artifact that
runs, not assumed from the source.  Works against any backend's HLO
(the CPU test mesh and the TPU AOT target name the op identically);
``--wire-bytes`` compiles the part3 step exact and int8 and asserts
the compressed build moves ≤ 1/3 of the exact build's bytes.
"""

from __future__ import annotations

import collections
import json
import re


# Collective kinds the async-window walker tracks (round 8: the
# analysis/program_audit passes reuse this walker for the zero1
# weight-update all-gather, so it is no longer permute-only).
ASYNC_COLLECTIVE_KINDS = (
    "collective-permute", "all-gather", "all-reduce", "reduce-scatter",
)
_KIND_ALT = "|".join(ASYNC_COLLECTIVE_KINDS)
_ASYNC_START_RE = re.compile(
    rf"%?(\S+) = .* ({_KIND_ALT})-start\(")
# A -done op closes the window its operand (the -start op) opened.  The
# operand list may spell the start's full tuple type inline
# (``collective-permute-done((f32[1066]{0:T(1024)}, ...) %cps.1)`` — the
# TPU backend does), so a lazy scan-to-first-paren mis-captures; instead
# the walker tokenizes everything after ``-done(`` and closes the first
# token that names an open window.
_ASYNC_DONE_RE = re.compile(rf"(?:{_KIND_ALT})-done\((.*)")
_NAME_TOKEN_RE = re.compile(r"%?([\w\.\-]+)")


def audit_schedule(hlo_text: str) -> dict:
    """Walk an optimized, scheduled HLO module; report per-async-window
    compute.  Returns a JSON-able summary dict.

    Tracks every async collective kind in :data:`ASYNC_COLLECTIVE_KINDS`
    (the ``-start``/``-done`` pairs); the legacy permute-only keys keep
    their meaning (``async_ppermute_pairs`` counts permute windows), and
    ``async_pairs_by_kind`` breaks all windows down per collective."""
    m = re.search(r"ENTRY [^\{]+\{(.*?)\n\}", hlo_text, re.S)
    if not m:
        raise ValueError("no ENTRY computation found in HLO text")
    compute_re = re.compile(
        r"%?(\S+) = .*?(fusion|convolution|dot|all-reduce(?!-)|"
        r"reduce-scatter(?!-))\("
    )
    open_pairs: dict[str, list] = {}
    open_kinds: dict[str, str] = {}
    in_flight, max_in_flight = 0, 0
    windows = []
    for line in m.group(1).splitlines():
        s = _ASYNC_START_RE.search(line)
        if s:
            open_pairs[s.group(1)] = []
            open_kinds[s.group(1)] = s.group(2)
            in_flight += 1
            max_in_flight = max(max_in_flight, in_flight)
            continue
        d = _ASYNC_DONE_RE.search(line)
        if d:
            name = next(
                (t for t in _NAME_TOKEN_RE.findall(d.group(1))
                 if t in open_pairs),
                None,
            )
            if name is not None:
                windows.append((name, open_kinds.pop(name),
                                open_pairs.pop(name)))
                in_flight -= 1
                continue
        c = compute_re.search(line)
        if c:
            for ops in open_pairs.values():
                ops.append((c.group(1), c.group(2)))
    # An op inside two concurrently-open windows counts once: the
    # metric is "distinct compute ops that execute under some in-flight
    # DMA", not a per-window tally.
    unique_ops = {name: kind for _, _, ops in windows for name, kind in ops}
    kinds = collections.Counter(unique_ops.values())
    permute = [w for w in windows if w[1] == "collective-permute"]
    return {
        "async_ppermute_pairs": len(permute),
        "pairs_with_compute_in_window": sum(
            1 for _, _, o in windows if o),
        "async_pairs_by_kind": dict(
            collections.Counter(k for _, k, _ in windows)),
        "pairs_with_compute_by_kind": dict(
            collections.Counter(k for _, k, o in windows if o)),
        "distinct_compute_ops_in_windows": len(unique_ops),
        "op_kinds_in_windows": dict(kinds),
        "max_concurrent_in_flight": max_in_flight,
    }


_SYNC_DEF_RE = re.compile(
    rf"%?([\w\.\-]+) = \(?\s*([a-z]+\d*\[[\d,]*\])[^=]*?"
    rf"\b({_KIND_ALT})(?!-start|-done)\(")


_GTE_RE = re.compile(
    r"%?([\w\.\-]+) = [^=]*get-tuple-element\([^%]*%([\w\.\-]+)\)"
)


def sync_collectives_from_hlo(hlo_text: str, kinds=None) -> list[dict]:
    """Every SYNC collective definition in the module — a collective
    issued without a ``-start``/``-done`` split sits on the critical
    path by construction (nothing can be scheduled under it).  Returns
    ``[{"name", "kind", "shape", "feeds_root"}]``; ``feeds_root`` is
    True when the op's result is a direct operand of its computation's
    ROOT — for a train step, the signature of a weight-update gather
    serialized against the step output (arxiv 2004.13336's target).
    Tuple-fused collectives (the TPU backend folds the gather into a
    variadic all-reduce whose elements reach ROOT via
    ``get-tuple-element``) are attributed through one GTE hop."""
    kinds = set(kinds or ASYNC_COLLECTIVE_KINDS)
    out = []
    root_operands: set[str] = set()
    gte_operand: dict[str, str] = {}
    for line in hlo_text.splitlines():
        stripped = line.strip()
        if stripped.startswith("ROOT "):
            root_operands.update(re.findall(r"%([\w\.\-]+)", stripped))
        g = _GTE_RE.search(line)
        if g:
            gte_operand[g.group(1)] = g.group(2)
        m = _SYNC_DEF_RE.search(line)
        if m and m.group(3) in kinds:
            out.append({"name": m.group(1), "kind": m.group(3),
                        "shape": m.group(2), "feeds_root": False})
    rooted = set(root_operands)
    rooted.update(op for gte, op in gte_operand.items()
                  if gte in root_operands)
    for rec in out:
        rec["feeds_root"] = rec["name"] in rooted
    return out


# HLO primitive-type widths (bytes) — the types a ring payload can carry
# (plus the widths the parser may meet in other programs' permutes).
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# A defining collective-permute line: ``%name = <shape> collective-permute(``
# or the async ``collective-permute-start(`` whose result is a tuple —
# group(1) grabs the FIRST shape either way, which for the start op is
# the operand buffer (counting the paired result buffer too would double
# every byte).  ``-done`` lines are uses of the start's buffers, skipped.
_CP_DEF_RE = re.compile(
    r"=\s*\(?\s*([a-z]+\d*\[[\d,]*\])[^=]*?\bcollective-permute"
    r"(?:-start)?\("
)

# The permute's routing table: ``source_target_pairs={{0,1},{1,2},...}``
# — the ground truth for attributing a compiled hop to a topology axis.
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{\d+,\d+\},?)*)\}")
_PAIR_RE = re.compile(r"\{(\d+),(\d+)\}")


def permute_pairs_from_line(line: str) -> list | None:
    """The ``source_target_pairs`` of one HLO line, or None."""
    m = _PAIRS_RE.search(line)
    if not m:
        return None
    return [(int(a), int(b)) for a, b in _PAIR_RE.findall(m.group(1))]


def _shape_bytes(shape: str) -> int:
    """``'f32[2,4]'`` → 32.  ``'f32[]'`` (scalar) → 4."""
    dtype, dims = shape.rstrip("]").split("[")
    if dtype not in _DTYPE_BYTES:
        raise ValueError(f"unknown HLO primitive type in {shape!r}")
    n = 1
    for d in filter(None, dims.split(",")):
        n *= int(d)
    return n * _DTYPE_BYTES[dtype]


def wire_bytes_from_hlo(hlo_text: str, inner: int | None = None) -> dict:
    """Sum every collective-permute's operand bytes across the module.

    Walks ALL computations (not just ENTRY — a while-body ring on some
    backends hides the permutes one call deep) and counts each
    *defining* occurrence once.  Returns ``{"total_bytes", "count",
    "by_dtype": {prim: bytes}}``.

    ``inner`` (round 11): also attribute each permute's bytes to a
    topology axis from its compiled ``source_target_pairs`` routing
    (``ops.topology.classify_permute_pairs`` over inner-major blocks of
    that size — imported at call time so this module stays importable
    without jax, while compiled and static attribution share ONE
    classifier), adding ``"by_axis": {"inner": bytes, "outer": bytes}``
    — the per-axis number DML103 pins against the static
    ``ring_wire_bytes_by_axis`` accounting.  A permute with no routing
    table (never seen from the jax lowerings audited here) is charged
    to the outer axis: over-counting the bottleneck link is the safe
    direction."""
    if inner is not None:
        from distributed_machine_learning_tpu.ops.topology import (
            classify_permute_pairs,
        )
    total = 0
    count = 0
    by_dtype: dict[str, int] = {}
    by_axis = {"inner": 0, "outer": 0}
    for line in hlo_text.splitlines():
        m = _CP_DEF_RE.search(line)
        if not m:
            continue
        b = _shape_bytes(m.group(1))
        total += b
        count += 1
        prim = m.group(1).split("[")[0]
        by_dtype[prim] = by_dtype.get(prim, 0) + b
        if inner is not None:
            pairs = permute_pairs_from_line(line)
            axis = ("outer" if pairs is None
                    else classify_permute_pairs(pairs, inner))
            by_axis[axis] += b
    out = {"total_bytes": total, "count": count, "by_dtype": by_dtype}
    if inner is not None:
        out["by_axis"] = by_axis
    return out


def compile_ring_hlo(mesh, length: int, *, compress: str = "none",
                     topk_frac: float = 0.125,
                     bucket_bytes: int | None = None,
                     mean: bool = True,
                     topology: str | None = None,
                     hd_max_bytes: int | None = None,
                     codec_impl: str = "xla") -> str:
    """jit-compile a bare bucketed ring all-reduce over ``mesh`` and
    return the optimized HLO text — backend-agnostic (the CPU test mesh
    compiles the same collective-permute program shape the TPU target
    does), so the wire-byte audit can run in CI without libtpu.

    ``topology`` ("INNERxOUTER", round 11): compile the hierarchical
    plan instead — ``compress`` becomes the OUTER axis's codec (the CLI
    mapping) and ``hd_max_bytes`` caps the selector's halving-doubling
    admissibility (``None`` lets the round-20 cost model decide, 0
    pins every bucket to the ring plans, a large value admits
    halving-doubling for every bucket it wins).

    ``codec_impl`` (round 13): compile the int8 codec as the fused
    Pallas kernels (``"pallas"``) instead of the XLA ops — the DML103
    audit runs both and asserts the kernel build moves the exact same
    collective-permute bytes (the fusion must never change the wire)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from distributed_machine_learning_tpu.ops.ring import (
        DEFAULT_BUCKET_BYTES,
        get_wire_scheme,
        ring_all_reduce,
    )
    from distributed_machine_learning_tpu.runtime.mesh import (
        shard_map_no_check,
    )

    axis = mesh.axis_names[0]
    n = mesh.shape[axis]
    scheme = get_wire_scheme(compress, topk_frac=topk_frac,
                             codec_impl=codec_impl)
    topo = None
    if topology is not None:
        from distributed_machine_learning_tpu.ops.topology import (
            Topology,
            parse_topology,
        )

        inner, outer = parse_topology(topology)
        if inner * outer != n:
            raise ValueError(
                f"topology {topology!r} does not factor the mesh's "
                f"{n}-device axis"
            )
        topo = Topology(
            inner, outer, outer_scheme=compress, topk_frac=topk_frac,
            codec_impl=codec_impl, hd_max_bytes=hd_max_bytes,
        )

    def per_device(x):
        out = ring_all_reduce(
            x.reshape(-1), axis, n, mean=mean,
            bucket_bytes=(bucket_bytes if bucket_bytes is not None
                          else DEFAULT_BUCKET_BYTES),
            scheme=None if compress == "none" else scheme,
            topology=topo,
        )
        return out[None]

    fn = jax.jit(shard_map_no_check(
        per_device, mesh=mesh, in_specs=P(axis), out_specs=P(axis)
    ))
    x = jax.ShapeDtypeStruct((n, length), jnp.float32)
    return fn.lower(x).compile().as_text()


def _tpu_topology_mesh(topology_name: str):
    """8-chip AOT mesh for a named TPU topology (compile-only client).
    Sets ``TPU_SKIP_MDS_QUERY`` so libtpu skips the GCE-metadata probe
    that otherwise stalls the compile-only client for minutes off-GCE."""
    import os

    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh

    topo = topologies.get_topology_desc(
        platform="tpu", topology_name=topology_name
    )
    devs = np.array(topo.devices)
    return Mesh(devs.reshape(devs.size), ("batch",))


def compile_zero1_hlo(mesh, global_batch: int = 256,
                      overlap: bool = True) -> dict:
    """Compile the zero1 train step for ``mesh`` (a CPU test mesh or a
    TPU AOT topology mesh) and return the optimized HLO text(s):
    ``{"update": ..., "gather": ...}`` for the overlap build,
    ``{"step": ...}`` for the sync baseline.  State shapes are built
    host-side (``flatten_padded`` + ``eval_shape``) so no device_put
    onto AOT devices is needed."""
    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.cli.common import (
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.models.vgg import VGGTest
    from distributed_machine_learning_tpu.parallel.fsdp import (
        flatten_padded,
    )
    from distributed_machine_learning_tpu.parallel.zero1 import (
        Zero1State,
        make_zero1_train_step,
    )

    axis = mesh.axis_names[0]
    n = mesh.shape[axis]
    model = VGGTest()
    st = init_model_and_state(model)
    flat, mom_flat, unravel, n_elems = flatten_padded(st, n)
    z1 = Zero1State(param_flat=flat, momentum_shards=mom_flat,
                    batch_stats=st.batch_stats, step=st.step, rng=st.rng,
                    config=st.config)
    zshape = jax.eval_shape(lambda: z1)
    x = jax.ShapeDtypeStruct((global_batch, 32, 32, 3), jnp.float32)
    y = jax.ShapeDtypeStruct((global_batch,), jnp.int32)
    step = make_zero1_train_step(model, mesh, unravel, n_elems,
                                 axis_name=axis, augment=False,
                                 overlap=overlap)
    if not overlap:
        return {"step": step.lower(zshape, x, y).compile().as_text()}
    upd = step.update_for(z1.config).lower(
        zshape.param_flat, zshape.momentum_shards, zshape.batch_stats,
        zshape.step, zshape.rng, x, y,
    ).compile().as_text()
    gat = step.gather_inner.lower(zshape.param_flat).compile().as_text()
    return {"update": upd, "gather": gat}


def zero1_overlap_audit(mesh, global_batch: int = 256) -> dict:
    """The ISSUE-9 acceptance audit, read off compiled artifacts:

    - sync baseline: the weight-update all-gather IS on the critical
      path (sync, feeding ROOT) — the 2004.13336 anti-pattern the
      overlap build exists to kill (on backends that rewrite the gather
      into an equivalent collective, that collective is reported);
    - overlap build, update program: contains NO all-gather (and no
      root-feeding collective of any kind) — the critical path ends at
      the updated shard;
    - overlap build, consume program: the bucketed ppermute ring; on
      backends with async collectives (the TPU AOT target) the hops
      must form non-empty async windows — DMAs with the other buckets'
      assembly scheduled under them, several concurrently in flight.
    """
    sync_hlo = compile_zero1_hlo(mesh, global_batch, overlap=False)["step"]
    ov = compile_zero1_hlo(mesh, global_batch, overlap=True)
    sync_colls = sync_collectives_from_hlo(sync_hlo)
    upd_colls = sync_collectives_from_hlo(ov["update"])
    upd_sched = audit_schedule(ov["update"])
    gat_sched = audit_schedule(ov["gather"])
    # The consume program must stay PERMUTE-CHAINED: sync permutes are
    # fine (the CPU backend emits them), but any non-permute collective
    # there is the gather re-serializing under a different op name, and
    # zero permutes at all means it regressed to a monolithic gather.
    gat_nonpermute = [c for c in sync_collectives_from_hlo(ov["gather"])
                      if c["kind"] != "collective-permute"]
    # wire_bytes_from_hlo counts every defining collective-permute,
    # sync AND -start forms, so it covers both backends' spellings.
    gat_permutes = wire_bytes_from_hlo(ov["gather"])["count"]
    pairs = gat_sched["async_pairs_by_kind"].get("collective-permute", 0)
    windows_nonempty = gat_sched["pairs_with_compute_by_kind"].get(
        "collective-permute", 0)
    return {
        "sync_build": {
            "critical_path_collectives": sync_colls,
            "gather_on_critical_path": any(
                c["feeds_root"] for c in sync_colls),
        },
        "overlap_build": {
            "update_all_gathers": [
                c for c in upd_colls if c["kind"] == "all-gather"],
            "update_root_feeding_collectives": [
                c for c in upd_colls if c["feeds_root"]],
            "update_schedule": upd_sched,
            "gather_sync_nonpermute_collectives": gat_nonpermute,
            "gather_permutes": gat_permutes,
            "gather_async_permute_pairs": pairs,
            "gather_windows_with_compute": windows_nonempty,
            "gather_max_in_flight": gat_sched["max_concurrent_in_flight"],
        },
        "passes": (
            not any(c["kind"] == "all-gather" for c in upd_colls)
            and not any(c["feeds_root"] for c in upd_colls)
            and not gat_nonpermute
            and gat_permutes > 0
            # Async windows are a property of backends that emit
            # -start/-done (TPU); on a sync-collective backend (CPU)
            # the structural checks above carry the gate.
            and (pairs == 0 or windows_nonempty > 0)
        ),
    }


def compile_part3_for_topology(topology_name: str = "v5e:2x4",
                               global_batch: int = 256,
                               ring_kwargs: dict | None = None) -> str:
    """AOT-compile the part3 ring train step (VGG-11+BN, 25 MB buckets)
    for a multi-chip TPU topology; return the optimized HLO text."""
    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.cli.common import (
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.models.vgg import VGG11
    from distributed_machine_learning_tpu.parallel.strategies import (
        get_strategy,
    )
    from distributed_machine_learning_tpu.train.step import make_train_step

    mesh = _tpu_topology_mesh(topology_name)
    model = VGG11(use_bn=True, compute_dtype=jnp.bfloat16)
    state_shape = jax.eval_shape(lambda: init_model_and_state(model))
    x = jax.ShapeDtypeStruct((global_batch, 32, 32, 3), jnp.float32)
    y = jax.ShapeDtypeStruct((global_batch,), jnp.int32)
    strategy = get_strategy("ring", **(ring_kwargs or {}))
    step = make_train_step(model, strategy, mesh=mesh)
    if getattr(strategy, "stateful", False):
        # Error-feedback strategies thread a residual pytree; lower the
        # inner 4-ary program with a zero-state shape struct.
        res = jax.eval_shape(
            lambda: step.fresh_sync_state(state_shape.params)
        )
        return step.inner.lower(state_shape, x, y, res).compile().as_text()
    return step.lower(state_shape, x, y).compile().as_text()


def wire_bytes_main(topology_name: str = "v5e:2x4",
                    global_batch: int = 256) -> dict:
    """Compile the part3 step exact and int8 for the TPU topology, sum
    each build's collective-permute bytes, and assert the compressed
    build moves ≤ 1/3 of the exact build's bytes."""
    exact = wire_bytes_from_hlo(
        compile_part3_for_topology(topology_name, global_batch)
    )
    int8 = wire_bytes_from_hlo(
        compile_part3_for_topology(
            topology_name, global_batch, ring_kwargs={"compress": "int8"}
        )
    )
    ratio = (int8["total_bytes"] / exact["total_bytes"]
             if exact["total_bytes"] else float("nan"))
    return {
        "metric": f"ring_wire_bytes_{topology_name.replace(':', '_')}",
        "exact": exact,
        "int8": int8,
        "int8_over_exact": ratio,
        "passes_leq_one_third": ratio <= 1 / 3,
    }


def main(argv=None) -> None:
    import argparse
    import sys

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--topology", default="v5e:2x4")
    parser.add_argument("--global-batch", default=256, type=int)
    parser.add_argument("--wire-bytes", action="store_true",
                        help="audit collective-permute payload bytes "
                             "(exact vs int8 ring) instead of the "
                             "overlap schedule; exits non-zero unless "
                             "the int8 build moves <= 1/3 of the exact "
                             "build's bytes")
    parser.add_argument("--zero1", action="store_true",
                        help="audit the overlap-aware zero1 weight "
                             "update (ISSUE 9): sync baseline's gather "
                             "on the critical path vs the overlap "
                             "build's shard-terminated update program "
                             "+ bucketed-ring consume program; exits "
                             "non-zero unless the overlap build "
                             "passes")
    parser.add_argument("--cpu-mesh", action="store_true",
                        help="with --zero1: audit against the local "
                             "8-device CPU mesh (structural checks "
                             "only — XLA:CPU emits sync collectives) "
                             "instead of the TPU AOT topology")
    args = parser.parse_args(argv)
    if args.wire_bytes:
        summary = wire_bytes_main(args.topology, args.global_batch)
        print(json.dumps(summary))
        if not summary["passes_leq_one_third"]:
            sys.exit(1)
        return
    if args.zero1:
        if args.cpu_mesh:
            from distributed_machine_learning_tpu.runtime.mesh import (
                ensure_host_devices,
                make_mesh,
            )

            ensure_host_devices(8)
            mesh = make_mesh(8)
        else:
            mesh = _tpu_topology_mesh(args.topology)
        summary = zero1_overlap_audit(mesh, args.global_batch)
        summary["metric"] = (
            f"zero1_overlap_audit_"
            f"{'cpu8' if args.cpu_mesh else args.topology.replace(':', '_')}"
        )
        print(json.dumps(summary))
        if not summary["passes"]:
            sys.exit(1)
        return
    summary = audit_schedule(
        compile_part3_for_topology(args.topology, args.global_batch)
    )
    summary["metric"] = (
        f"ring_overlap_audit_{args.topology.replace(':', '_')}"
    )
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
