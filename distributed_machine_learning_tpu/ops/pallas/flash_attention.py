"""Flash attention (causal) as Pallas TPU kernels — forward AND backward.

The hot op of the transformer family, written for the hardware per the
Pallas playbook (/opt/skills/guides/pallas_guide.md): the L×L score
matrix never hits HBM in either direction, and on-chip memory is
O(block), not O(L).

Forward: grid (batch·heads, Q blocks, K blocks) with the K dimension
innermost, so Pallas streams one [block_k, D] K/V tile into VMEM per
step while the online-softmax running (max, normalizer, accumulator)
triple persists in VMEM scratch across the K steps of each Q block.
Blocks entirely above the causal diagonal skip their compute via
``pl.when`` AND their DMA: the K/V index map clamps the block index to
the last in-range tile, and Pallas elides copies whose block index did
not change between grid steps — so causal masking saves both halves of
the work, not just the FLOPs.  The forward also emits the per-row
logsumexp — the one O(L) residual the backward needs.

Backward: the standard two-kernel flash-bwd split (no atomics needed —
each kernel owns its accumulator):

- **dQ kernel**, grid (BH, Q blocks, K blocks): recomputes each score
  block from Q/K and the saved logsumexp (``p = exp(s − lse)``), forms
  ``ds = p·(dp − Δ)`` with ``Δ = rowsum(dO ∘ O)`` precomputed outside,
  and accumulates ``dq += ds·K`` in VMEM scratch over the K steps.
- **dK/dV kernel**, grid (BH, K blocks, Q blocks): same recomputation
  with Q innermost, accumulating ``dv += pᵀ·dO`` and ``dk += dsᵀ·Q``.

MXU discipline: matmuls run on the INPUT dtype (bf16 in training) with
``preferred_element_type=f32`` accumulation — a bf16×bf16→f32 matmul is
a single MXU pass, where an f32×f32 matmul costs several (XLA's own
attention runs bf16 too, so anything else loses to dense by
construction).  The online-softmax state (m, l, acc) stays f32.

Blocks are picked per L from an on-chip sweep: 512×512 squares for
both kernels (see ``_fwd_blocks``) — large stationary blocks buy
arithmetic intensity, and the sweep showed the streamed block also
wants to be large (fewer grid steps, bigger MXU tiles) rather than
held at MXU width; smaller powers of two engage only when L demands.

Total backward traffic is O(L·D) per tensor plus the recomputed block
matmuls — the memory profile that lets long-context training fit, where
the XLA dense VJP would materialize the [H, L, L] probability tensor.

On non-TPU backends the kernels run in interpreter mode, so tests on
the CPU mesh exercise the identical code path the TPU compiles.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Interpret-mode selection and the pltpu import are the shared knobs of
# ops/pallas/common.py (one decision for every kernel); ``_interpret``
# stays importable from here — quant_matmul historically imported it
# from this module, and that path keeps working as an alias.
from distributed_machine_learning_tpu.ops.pallas.common import (
    _HAS_PLTPU,
    _interpret,
    pltpu,
)

NEG_INF = -1e30
_LANES = 128  # VMEM lane width: m/l scratch is (block_q, _LANES)
# The kernels run the softmax in BASE 2: scores are pre-scaled by
# log2(e) so every exp becomes a bare exp2.  m, l's log-offset, and the
# saved lse therefore live in log2 space; probabilities and outputs are
# unchanged because exp2((s·log2e) − m2) == exp(s − m).
#
# Measured context (8k ablation at constant FLOPs): the exp over the
# score tile IS the kernel's critical path — per-tile time is ~2.2 µs
# regardless of head dim, i.e. one exp per score element at the VPU's
# ~118 Gelem/s transcendental rate, with the MXU work hidden under it.
# That makes the performed-FLOPs roofline exp-bound at 4·D FLOPs per
# exp: 30 TF/s at D=64, 60 TF/s at D=128 — this kernel reaches ~90%
# and ~94% of those ceilings.  (exp2 itself measured neutral vs exp
# under Mosaic — its exp is already pow2-based — but base-2 keeps the
# kernel at the floor of what the lowering can emit.)
LOG2E = 1.4426950408889634


def _compiler_params():
    """batch·head and the stationary block axis are parallel; the
    streamed (innermost) axis carries the scratch accumulator between
    steps and must stay sequential."""
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary")
    )


def _pick(L: int, target: int) -> int:
    """Largest power-of-two block <= target that divides L."""
    b = 1
    for c in (2, 4, 8, 16, 32, 64, 128, 256, 512):
        if c <= target and c <= L and L % c == 0:
            b = c
    return b


def _needs_pad(L: int) -> bool:
    """True when L cannot be tiled legally as-is: Mosaic requires the
    residuals' lane-dim block (== block_q) to be a multiple of 128 or
    the full array dim, so a length whose largest power-of-two divisor
    is <128 (and which isn't itself that divisor) must be padded."""
    bq = _pick(L, 512)
    return not (bq % 128 == 0 or bq == L)


def _padded_len(L: int) -> int:
    """Smallest multiple of 512 (the tuned block size) >= L."""
    return -(-L // 512) * 512


def flash_wins(L: int) -> bool:
    """Length policy shared by every "auto" dispatch: after the 512×512
    block retune the flash kernels beat XLA dense attention from 512
    context up on the measured chip (512k vs 421k tok/s @512; 1.6× @1k;
    ~3× @4-8k — docs/PERF.md) and are the only option past ~8-16k where
    dense's L² program stops compiling.  Dense still wins at 256 (584k
    vs 479k) and at sub-2k lengths with degraded blocks: sub-1k lengths
    not divisible by 512 forfeit the thin @512 margin, and 1-2k lengths
    whose largest power-of-two divisor is under 128 would pay the pad-
    to-512-multiple overhead (up to (L+511)²/L² ≈ 1.5× at 1k) against
    only a ~1.6× dense deficit.  From 2048 up flash wins for EVERY
    length — padded if needed — because dense is ≥2× behind (and soon
    uncompilable) while the pad overhead shrinks quadratically."""
    if L >= 2048:
        return True
    if L >= 1024:
        return not _needs_pad(L)
    return L >= 512 and _pick(L, 512) == 512


def _fwd_blocks(L: int) -> tuple[int, int]:
    # Measured sweep on the attached chip (d_model 512, D=64, seq 4k):
    # square 512×512 beats every rectangular candidate — 283k tok/s vs
    # 237k for (512,256), 185k for (512,128) — the bigger streamed block
    # amortizes per-grid-step overhead and the MXU prefers the larger
    # contraction tiles; VMEM stays ~1 MB/core at D=64.
    return _pick(L, 512), _pick(L, 512)


def _dkv_blocks(L: int) -> tuple[int, int]:
    # Same sweep for the dK/dV kernel: (512,512) gives 301k tok/s vs
    # 284k for the old (256,512) and 235k for (256,256).
    return _pick(L, 512), _pick(L, 512)


def _last_kb(qi, block_q: int, block_k: int):
    """Last K block index intersecting the causal triangle of Q block qi."""
    return ((qi + 1) * block_q - 1) // block_k


def _first_qi(kb, block_q: int, block_k: int):
    """First Q block index intersecting the causal triangle of K block kb."""
    return (kb * block_k) // block_q


def _tile_classes(q_start, k_start, block_q: int, block_k: int):
    """(interior, on_diag) predicates for one (Q, K) tile of a causal
    kernel.  ``interior``: every (q_pos, k_pos) pair satisfies
    k_pos <= q_pos — the tile needs NO mask.  ``on_diag``: the tile
    straddles the diagonal and must mask.  Tiles above the diagonal
    match neither and are skipped entirely."""
    interior = k_start + block_k - 1 <= q_start
    active = k_start <= q_start + block_q - 1
    return interior, active & jnp.logical_not(interior)


def _dispatch_tiles(do_update, q_start, k_start, block_q: int, block_k: int,
                    causal: bool):
    """Shared tile dispatch for every flash/ring kernel: causal kernels
    run the mask-free variant on tiles fully below the diagonal (the
    per-tile iota/compare/select mask is VPU work rivaling the tile's
    MXU time, and only diagonal-straddling tiles need it), the masked
    variant on the diagonal, and skip above-diagonal tiles; non-causal
    kernels run every tile mask-free.  ``do_update(tile_causal)`` is the
    kernel-specific tile body."""
    if not causal:
        do_update(False)
        return
    interior, on_diag = _tile_classes(q_start, k_start, block_q, block_k)

    @pl.when(interior)
    def _update_full():
        do_update(False)

    @pl.when(on_diag)
    def _update_diag():
        do_update(True)


def _block_scores(q, k, q_start, k_start, block_q, block_k, scale):
    """Masked scaled scores for one (Q, K) tile — shared fwd/bwd.

    The dot runs on the input dtype (bf16 on the training path) with f32
    accumulation: one MXU pass instead of the multi-pass f32 emulation.
    """
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    q_pos = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = k_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


def _full_scores(q, k, scale):
    """Unmasked scaled scores (ring steps where every key precedes every
    query)."""
    return jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale


# --- Shared per-tile math (single source of truth for the subtle kernel
# --- arithmetic; the flash kernels here and the ring-flash chunk kernels
# --- in ring_flash_attention.py all call these).


def _tile_scores(q, k, q_start, k_start, block_q, block_k, scale,
                 causal: bool):
    """Scores for one tile; callers on the log2-softmax path pass
    ``scale * LOG2E`` so the downstream exps become exp2."""
    if causal:
        return _block_scores(q, k, q_start, k_start, block_q, block_k, scale)
    return _full_scores(q, k, scale)


def _online_update(s, m, l, acc, v, causal: bool):
    """One online-softmax block update of the (m, l, acc) running triple.
    ``s`` fp32 scores [bq, bk] in LOG2 space (pre-scaled by log2e);
    m [bq] log2-space running max; l [bq]; acc [bq, D] fp32."""
    m_new = jnp.maximum(m, s.max(axis=-1))
    alpha = jnp.exp2(m - m_new)
    p = jnp.exp2(s - m_new[:, None])
    if causal:
        # Masked entries must contribute 0 even in a fully-masked row
        # (there s == m_new == NEG_INF and the exp above gives 1, not 0).
        p = jnp.where(s > 0.5 * NEG_INF, p, 0.0)
    l_new = l * alpha + p.sum(axis=-1)
    acc_new = acc * alpha[:, None] + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, acc_new


def _p_from_lse(s, lse, causal: bool):
    """``s`` and ``lse`` both in log2 space."""
    p = jnp.exp2(s - lse[:, None])
    if causal:
        p = jnp.where(s > 0.5 * NEG_INF, p, 0.0)
    return p


def _dq_contrib(s, k, v, do, lse, delta, scale, causal: bool):
    """dq += ds·K for one tile (backward recompute from the saved lse)."""
    p = _p_from_lse(s, lse, causal)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta[:, None]) * scale
    return jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _dkv_contrib(s, q, v, do, lse, delta, scale, causal: bool):
    """(dv += pᵀ·dO, dk += dsᵀ·Q) for one tile."""
    p = _p_from_lse(s, lse, causal)
    dv_c = jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta[:, None]) * scale
    dk_c = jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return dk_c, dv_c


def _flash_fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
    *, block_q, block_k, scale,
):
    """One (Q block, K block) tile of the online-softmax recurrence."""
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    q_start = qi * block_q
    k_start = kb * block_k

    @pl.when(kb == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _do_update(causal):
        q = q_ref[0]  # [block_q, D], input dtype
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]
        s = _tile_scores(q, k, q_start, k_start, block_q, block_k, scale * LOG2E,
                         causal=causal)
        m_new, l_new, acc_new = _online_update(
            s, m_ref[:, 0], l_ref[:, 0], acc_ref[:], v, causal=causal
        )
        acc_ref[:] = acc_new
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    # Blocks entirely above the causal diagonal are skipped (their DMA
    # is already elided by the clamped index map).  Blocks entirely
    # BELOW it — the vast majority at long L — run the mask-free
    # variant: the per-tile iota/compare/select mask is pure VPU work
    # that rivals the tile's MXU time, and only tiles straddling the
    # diagonal need it.
    _dispatch_tiles(_do_update, q_start, k_start, block_q, block_k,
                    causal=True)

    @pl.when(kb == pl.num_programs(2) - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0] = (acc_ref[:] / l[:, None]).astype(o_ref.dtype)
        # Exact [block_q] logsumexp row — sequence in the LANE dim, one
        # sublane (the splash-attention residual layout).  The r2 kernels
        # stored this 128-lane-replicated; since the backward kernels
        # re-fetch the lse/Δ tiles on every grid step whose block index
        # changes, that replication multiplied the O(L) residual reads
        # by 128× (~17 GB per dK/dV pass at 32k).  The sublane→lane
        # relayout here costs one in-register transpose per Q block.
        # Stored in LOG2 space, matching the kernels' base-2 softmax.
        lse_ref[0] = m_ref[:, 0] + jnp.log2(l)


def _flash_fwd(q, k, v, block_q: int, block_k: int, kv_groups: int = 1):
    """q: [BHq, L, D], k/v: [BHq // kv_groups, L, D] →
    (out [BHq, L, D], lse [BHq, 1, L] fp32 — exact rows, not
    lane-replicated).

    ``kv_groups > 1`` is grouped-query attention natively: the K/V tile
    index maps divide the batch·head grid index by the group factor, so
    the narrow K/V are streamed as-is — no [BHq, L, D] repeat ever hits
    HBM, cutting K/V read traffic by the group factor.  (Folding puts
    heads fastest-varying, so bh // kv_groups is exactly the query
    head's KV group — the jnp.repeat(axis=2) convention.)"""
    BH, L, D = q.shape
    scale = 1.0 / (D**0.5)
    grid = (BH, L // block_q, L // block_k)
    kernel = functools.partial(
        _flash_fwd_kernel, block_q=block_q, block_k=block_k, scale=scale
    )
    if not _HAS_PLTPU:  # pragma: no cover — pltpu ships with jax[tpu]/cpu alike
        raise RuntimeError("pallas TPU support (jax.experimental.pallas.tpu) "
                           "is unavailable; use attn_impl='dense'")
    q_spec = pl.BlockSpec(
        (1, block_q, D), lambda bh, qi, kb: (bh, qi, 0), memory_space=pltpu.VMEM
    )
    # Clamp above-diagonal K/V fetches to the diagonal tile: the index
    # repeats, so Pallas skips the copy (causal DMA elision).
    k_spec = pl.BlockSpec(
        (1, block_k, D),
        lambda bh, qi, kb: (
            bh // kv_groups, jnp.minimum(kb, _last_kb(qi, block_q, block_k)), 0
        ),
        memory_space=pltpu.VMEM,
    )
    # (None, 1, block_q) block of a [BH, 1, L] array: the singleton
    # middle dim satisfies Mosaic's block-shape rule (last two dims
    # (1, block_q) — 1 equals the array dim, block_q % 128 == 0) while
    # keeping the stored residual exact.  Same trick as splash attention.
    lse_spec = pl.BlockSpec(
        (None, 1, block_q), lambda bh, qi, kb: (bh, 0, qi),
        memory_space=pltpu.VMEM,
    )
    scratch = [
        pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
        pltpu.VMEM((block_q, _LANES), jnp.float32),  # running normalizer
        pltpu.VMEM((block_q, D), jnp.float32),  # output accumulator
    ]
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((BH, L, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, L), jnp.float32),
        ),
        grid=grid,
        in_specs=[q_spec, k_spec, k_spec],
        out_specs=(q_spec, lse_spec),
        scratch_shapes=scratch,
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q, k, v)


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
    *, block_q, block_k, scale,
):
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    q_start = qi * block_q
    k_start = kb * block_k

    @pl.when(kb == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _do_update(causal):
        k = k_ref[0]
        v = v_ref[0]
        s = _tile_scores(q_ref[0], k, q_start, k_start, block_q, block_k,
                         scale * LOG2E, causal=causal)
        dq_acc[:] = dq_acc[:] + _dq_contrib(
            s, k, v, do_ref[0], lse_ref[0], delta_ref[0],
            scale, causal=causal,
        )

    _dispatch_tiles(_do_update, q_start, k_start, block_q, block_k,
                    causal=True)

    @pl.when(kb == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc, *, block_q, block_k, scale,
):
    kb = pl.program_id(1)
    qi = pl.program_id(2)
    q_start = qi * block_q
    k_start = kb * block_k

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _do_update(causal):
        q = q_ref[0]
        v = v_ref[0]
        s = _tile_scores(q, k_ref[0], q_start, k_start, block_q, block_k,
                         scale * LOG2E, causal=causal)
        dk_c, dv_c = _dkv_contrib(
            s, q, v, do_ref[0], lse_ref[0], delta_ref[0],
            scale, causal=causal,
        )
        dk_acc[:] = dk_acc[:] + dk_c
        dv_acc[:] = dv_acc[:] + dv_c

    _dispatch_tiles(_do_update, q_start, k_start, block_q, block_k,
                    causal=True)

    @pl.when(qi == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, do, lse, delta, kv_groups: int = 1):
    """q/do/lse/delta: [BHq, ...], k/v: [BHq // kv_groups, L, D] →
    (dq [BHq, L, D], dk, dv [BHq, L, D] — PER QUERY HEAD; the caller
    group-sums dk/dv down to the narrow KV heads, one cheap XLA
    reduction, while the kernels never materialize repeated K/V)."""
    BH, L, D = q.shape
    scale = 1.0 / (D**0.5)

    block_q, block_k = _fwd_blocks(L)  # dQ kernel: Q stationary, like fwd
    q_spec_q = pl.BlockSpec(
        (1, block_q, D), lambda bh, qi, kb: (bh, qi, 0), memory_space=pltpu.VMEM
    )
    k_spec_q = pl.BlockSpec(
        (1, block_k, D),
        lambda bh, qi, kb: (
            bh // kv_groups, jnp.minimum(kb, _last_kb(qi, block_q, block_k)), 0
        ),
        memory_space=pltpu.VMEM,
    )
    # lse/Δ ride as exact (1, block_q) rows of [BH, 1, L] — sequence in
    # lanes, no replication; in-kernel use pays one lane→sublane
    # relayout per tile.
    row_spec_q = pl.BlockSpec(
        (None, 1, block_q), lambda bh, qi, kb: (bh, 0, qi),
        memory_space=pltpu.VMEM,
    )
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, block_q=block_q, block_k=block_k,
            scale=scale,
        ),
        out_shape=jax.ShapeDtypeStruct((BH, L, D), q.dtype),
        grid=(BH, L // block_q, L // block_k),
        in_specs=[q_spec_q, k_spec_q, k_spec_q, q_spec_q, row_spec_q,
                  row_spec_q],
        out_specs=q_spec_q,
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    # dK/dV: K blocks own the accumulators, Q innermost.  Below-diagonal
    # Q/dO fetches clamp to the first in-range tile (DMA elision).
    block_q, block_k = _dkv_blocks(L)
    q_spec_k = pl.BlockSpec(
        (1, block_q, D),
        lambda bh, kb, qi: (
            bh, jnp.maximum(qi, _first_qi(kb, block_q, block_k)), 0
        ),
        memory_space=pltpu.VMEM,
    )
    # K/V input tiles read the narrow heads; the dk/dv OUTPUTS stay per
    # query head (out_specs use bh as-is) — accumulating across a group
    # inside the kernel would serialize the bh grid axis, so the group
    # sum happens outside in XLA instead.
    kv_in_spec = pl.BlockSpec(
        (1, block_k, D), lambda bh, kb, qi: (bh // kv_groups, kb, 0),
        memory_space=pltpu.VMEM,
    )
    k_spec_k = pl.BlockSpec(
        (1, block_k, D), lambda bh, kb, qi: (bh, kb, 0), memory_space=pltpu.VMEM
    )
    row_spec_k = pl.BlockSpec(
        (None, 1, block_q),
        lambda bh, kb, qi: (
            bh, 0, jnp.maximum(qi, _first_qi(kb, block_q, block_k))
        ),
        memory_space=pltpu.VMEM,
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, block_q=block_q, block_k=block_k,
            scale=scale,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((BH, L, D), k.dtype),
            jax.ShapeDtypeStruct((BH, L, D), v.dtype),
        ),
        grid=(BH, L // block_k, L // block_q),
        in_specs=[q_spec_k, kv_in_spec, kv_in_spec, q_spec_k, row_spec_k,
                  row_spec_k],
        out_specs=(k_spec_k, k_spec_k),
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def _fold(a):
    B, L, H, D = a.shape
    return a.transpose(0, 2, 1, 3).reshape(B * H, L, D)


def _unfold(a, B, H):
    BH, L, D = a.shape
    return a.reshape(B, H, L, D).transpose(0, 2, 1, 3)


def _kv_groups(q, k, v) -> int:
    if k.shape != v.shape:
        raise ValueError(
            f"k and v must have identical shapes, got {k.shape} vs {v.shape}"
        )
    H, Hkv = q.shape[2], k.shape[2]
    if H % Hkv:
        raise ValueError(
            f"query heads {H} must be a multiple of K/V heads {Hkv}"
        )
    return H // Hkv


@jax.custom_vjp
def _flash_core(q, k, v):
    B, L, H, D = q.shape
    bq, bk = _fwd_blocks(L)
    out, _ = _flash_fwd(
        _fold(q), _fold(k), _fold(v), bq, bk, kv_groups=_kv_groups(q, k, v)
    )
    return _unfold(out, B, H)


def _flash_core_fwd(q, k, v):
    B, L, H, D = q.shape
    bq, bk = _fwd_blocks(L)
    out, lse = _flash_fwd(
        _fold(q), _fold(k), _fold(v), bq, bk, kv_groups=_kv_groups(q, k, v)
    )
    return _unfold(out, B, H), (q, k, v, out, lse)


def _flash_core_bwd(res, g):
    q, k, v, out, lse = res  # out/lse already folded [BH, ...]
    B, L, H, D = q.shape
    groups = _kv_groups(q, k, v)
    do = _fold(g)
    # Δ = rowsum(dO ∘ O): O(L·D) elementwise — XLA fuses it; no kernel
    # needed.
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )[:, None, :]  # [BH, 1, L] — exact, same layout as the saved lse
    dq, dk, dv = _flash_bwd(
        _fold(q), _fold(k), _fold(v), do, lse, delta, kv_groups=groups
    )
    dq = _unfold(dq, B, H)
    dk = _unfold(dk, B, H)  # [B, L, H, D] — per query head
    dv = _unfold(dv, B, H)
    if groups > 1:
        # Group-sum down to the narrow KV heads: query heads of one KV
        # group are contiguous (h // groups == kv head), so a reshape
        # exposes the group axis.
        Hkv = H // groups
        dk = dk.reshape(B, L, Hkv, groups, D).sum(axis=3)
        dv = dv.reshape(B, L, Hkv, groups, D).sum(axis=3)
    return dq, dk, dv


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_self_attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """Causal flash attention: q [B, L, H, D] in, [B, L, H, D] out.

    Drop-in for ``ops.ring_attention.dense_self_attention`` on contiguous
    (offset-0) sequences — the unsharded model path.  Both directions run
    as Pallas kernels (O(block) on-chip memory; the backward recomputes
    score blocks from the forward's saved logsumexp).

    Grouped-query attention is native: pass k/v with Hkv < H heads
    (Hkv | H, the ``jnp.repeat``-convention grouping) and the kernels
    stream the narrow K/V directly — no repeated K/V is ever
    materialized in HBM, so K/V read traffic drops by the group factor
    (see ``models/transformer.py``'s flash branch).

    Total over every L: lengths Mosaic cannot tile natively (largest
    power-of-two divisor < 128) are zero-padded up to the next 512
    multiple and the output sliced back.  Zero padding is exact for
    causal attention — padded KEYS sit after every real query (their
    tiles are entirely above the diagonal: skipped), and padded QUERY
    rows are discarded by the slice while contributing zero to dK/dV in
    the backward (their dO rows are zero).  The pad/slice sits OUTSIDE
    the custom_vjp, so JAX's pad/slice VJPs route gradients correctly.
    """
    L = q.shape[1]
    if not _needs_pad(L):
        return _flash_core(q, k, v)
    pad = ((0, 0), (0, _padded_len(L) - L), (0, 0), (0, 0))
    return _flash_core(
        jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad)
    )[:, :L]
