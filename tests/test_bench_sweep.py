"""Weak-scaling sweep harness (bench/sweep.py) on the virtual CPU mesh."""

import jax
import numpy as np

from distributed_machine_learning_tpu.bench.sweep import (
    run_point,
    weak_scaling_sweep,
)
from distributed_machine_learning_tpu.models.vgg import VGGTest


def test_weak_scaling_sweep_structure():
    model = VGGTest()
    points = weak_scaling_sweep(
        model, "ring", device_counts=[1, 2], per_device_batch=4, timed_iters=2
    )
    assert [p.num_devices for p in points] == [1, 2]
    assert points[0].strategy == "none"  # baseline: part1 path, no mesh
    assert points[1].strategy == "ring"
    for p in points:
        assert p.imgs_per_sec > 0
        assert np.isclose(
            p.imgs_per_sec_per_device, p.imgs_per_sec / p.num_devices, rtol=1e-2
        )
    assert points[0].efficiency == 1.0
    assert points[1].efficiency is not None and points[1].efficiency > 0


def test_run_point_does_not_consume_shared_state():
    """run_point must deep-copy a provided init state (steps donate it)."""
    from distributed_machine_learning_tpu.cli.common import init_model_and_state

    model = VGGTest()
    state = init_model_and_state(model)
    run_point(model, "all_reduce", 2, per_device_batch=4, timed_iters=1,
              init_state=state)
    # Re-usable: a second point from the same state object still works.
    p = run_point(model, "all_reduce", 2, per_device_batch=4, timed_iters=1,
                  init_state=state)
    assert p.imgs_per_sec > 0
