# dmlcheck-virtual-path: distributed_machine_learning_tpu/train/fixture.py
"""DML003 clean case: the restore result is re-materialized through
fresh_buffers before the donating step sees it."""
from distributed_machine_learning_tpu.train.checkpoint import fresh_buffers


def resume(ckptr, path, train_step, x, y):
    state = ckptr.restore(path)
    state = fresh_buffers(state)     # XLA-owned buffers, donation-safe
    return train_step(state, x, y)
