"""Per-iteration timing harness.

Reproduces the reference's measurement protocol (``part1/main.py:36,53-58``):
wall-clock per iteration, iteration 0 excluded as warm-up, totals and the
average over the remaining iterations printed at the end.  On TPU the
warm-up iteration is where XLA compilation lands, so excluding iteration 0
is exactly the right protocol here too — but the caller must block on the
device result (``jax.block_until_ready``) before stopping the clock, since
JAX dispatch is asynchronous (unlike the reference's synchronous CPU torch).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class IterationTimer:
    """Accumulates per-iteration wall-clock, excluding `skip_first` iters.

    The reference runs 40 iterations and divides total by 39
    (``part1/main.py:53-58``): iteration 0 is measured but not accumulated.
    """

    skip_first: int = 1
    times: list = field(default_factory=list)
    _start: float = 0.0
    _iter: int = 0

    def start(self) -> None:
        self._start = time.perf_counter()

    def stop(self) -> float:
        """Stop the clock; returns this iteration's time (always), and
        accumulates it unless it is among the first `skip_first` iters."""
        elapsed = time.perf_counter() - self._start
        if self._iter >= self.skip_first:
            self.times.append(elapsed)
        self._iter += 1
        return elapsed

    @property
    def total(self) -> float:
        return sum(self.times)

    @property
    def average(self) -> float:
        return self.total / len(self.times) if self.times else 0.0

    @property
    def count(self) -> int:
        return len(self.times)

    def summary(self) -> str:
        # Same print surface as the reference (part1/main.py:57-58).
        return (
            f"Total execution time is : {self.total} seconds\n"
            f"Average execution time is  : {self.average} seconds"
        )
