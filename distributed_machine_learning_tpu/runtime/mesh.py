"""Device-mesh construction.

The reference's "mesh" is a gloo process group over TCP
(``dist.init_process_group`` — ``part2/2a/main.py:197``).  Here the unit
of parallelism is a ``jax.sharding.Mesh`` over TPU chips; the data axis
(``"batch"``) plays the role of the gloo world, with XLA collectives
riding ICI.  The mesh is 1-D for the reference's data-parallel-only
capability surface (SURVEY.md §2.3) but constructed through a general
helper so additional axes (model/pipeline/sequence) slot in without
touching callers.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import jax
from jax.sharding import Mesh

try:  # jax>=0.6 exposes shard_map at top level
    from jax import shard_map as _shard_map_impl
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_impl

BATCH_AXIS = "batch"

# The state layouts a checkpoint can be saved under (and resharded
# between worlds within): replicated data parallelism, ZeRO-1
# (params replicated / momentum sharded), and ZeRO-3/FSDP (both
# sharded).  The flat-shard layouts pad their vectors to a multiple of
# the world size, which is exactly what a world-size change must redo.
SHARD_LAYOUTS = ("dp", "zero1", "fsdp")


def padded_len(n_elems: int, world: int) -> int:
    """Length of the flat param/momentum vectors after padding to a
    multiple of ``world`` — the canonical definition shared by the
    flat-shard schemes (``parallel/fsdp.py``, ``parallel/zero1.py``)
    and the checkpoint resharder, so partition boundaries recompute
    identically everywhere."""
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    return -(-n_elems // world) * world


@dataclasses.dataclass(frozen=True)
class ShardSpec:
    """How a training state is laid out across a data-parallel world —
    the metadata a checkpoint must carry for a restore onto a
    *different* world size to be possible.

    ``layout``: one of :data:`SHARD_LAYOUTS`.  ``world``: the data-axis
    size the state was built for.  ``n_elems``: the unpadded length of
    the flat param/momentum vectors (zero1/fsdp — the *logical* array a
    reshard preserves bit-for-bit; None for dp, whose leaves carry no
    padding).
    """

    layout: str
    world: int
    n_elems: int | None = None

    def __post_init__(self):
        if self.layout not in SHARD_LAYOUTS:
            raise ValueError(
                f"unknown layout {self.layout!r}; known: {SHARD_LAYOUTS}"
            )
        if self.world < 1:
            raise ValueError(f"world must be >= 1, got {self.world}")
        if self.layout != "dp" and self.n_elems is None:
            raise ValueError(
                f"layout {self.layout!r} needs n_elems (the unpadded "
                "flat length) to recompute partition boundaries"
            )

    @property
    def padded(self) -> int | None:
        """The padded flat length under this spec, or None for dp."""
        return None if self.n_elems is None else padded_len(
            self.n_elems, self.world
        )

    def with_world(self, world: int) -> "ShardSpec":
        """The same layout re-laid-out for a different world size."""
        return dataclasses.replace(self, world=world)

    def as_dict(self) -> dict:
        return {"layout": self.layout, "world": self.world,
                "n_elems": self.n_elems}

    @classmethod
    def from_dict(cls, payload: dict) -> "ShardSpec":
        return cls(
            layout=str(payload["layout"]), world=int(payload["world"]),
            n_elems=(None if payload.get("n_elems") is None
                     else int(payload["n_elems"])),
        )


def repad_flat(flat: np.ndarray, n_elems: int, world: int) -> np.ndarray:
    """Re-lay-out one flat padded vector for a new world size: keep the
    logical prefix ``flat[:n_elems]`` bit-for-bit, recompute the padded
    length for ``world``, and zero-fill the new tail.  The whole of a
    zero1/fsdp reshard is this, applied per flat leaf — padding is the
    only world-size-dependent part of the layout."""
    flat = np.asarray(flat)
    if flat.ndim != 1:
        raise ValueError(f"expected a flat vector, got shape {flat.shape}")
    if flat.shape[0] < n_elems:
        raise ValueError(
            f"flat vector of {flat.shape[0]} elements cannot hold "
            f"n_elems={n_elems} logical values"
        )
    out = np.zeros((padded_len(n_elems, world),), dtype=flat.dtype)
    out[:n_elems] = flat[:n_elems]
    return out


def shard_map_no_check(f, *, mesh, in_specs, out_specs, manual_axes=None):
    """shard_map with replication checking off, across the API rename
    (new jax: check_vma; the experimental API this falls back to: check_rep).

    ``manual_axes``: restrict manual sharding to a subset of mesh axes
    (jax's ``axis_names``); the rest stay under automatic GSPMD
    propagation — how the 3-D step composes a manual ppermute pipeline
    with compiler-derived tensor/data parallelism
    (``parallel/parallel3d.py``).  None (default) = fully manual.
    """
    kwargs = {} if manual_axes is None else {"axis_names": frozenset(manual_axes)}
    try:
        return _shard_map_impl(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False, **kwargs,
        )
    except TypeError as e:  # pragma: no cover
        if manual_axes is not None:
            raise RuntimeError(
                "partial-manual shard_map (manual_axes=...) needs a jax "
                "version whose shard_map accepts the axis_names parameter; "
                "this jax only has the legacy check_rep API"
            ) from e
        return _shard_map_impl(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )


def ensure_host_devices(n: int = 8) -> None:
    """Put ``--xla_force_host_platform_device_count=n`` into XLA_FLAGS
    if no device-count flag is present yet.

    MUST run before the CPU client spins up (the first ``jax.devices()``
    call) — after that the flag is ignored.  The ONE copy of the dance
    the virtual-mesh entrypoints share (the dmlcheck CLI, the overlap
    bench/audit ``--cpu-mesh`` paths), so the device count and the
    ordering invariant cannot drift between them.  tests/conftest.py
    keeps its own inline copy deliberately: it must mutate the env
    before importing ANYTHING from this package."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()


def make_mesh(
    num_devices: int | None = None,
    axis_names: tuple[str, ...] = (BATCH_AXIS,),
    axis_shape: tuple[int, ...] | None = None,
    devices=None,
) -> Mesh:
    """Build a Mesh over (a prefix of) the available devices.

    With defaults: a 1-D data-parallel mesh over all devices.  Pass
    ``axis_names``/``axis_shape`` for multi-axis layouts, e.g.
    ``axis_names=("batch", "model"), axis_shape=(4, 2)``.
    """
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, have {len(devices)}"
            )
        devices = devices[:num_devices]
    if axis_shape is None:
        axis_shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    if int(np.prod(axis_shape)) != len(devices):
        raise ValueError(f"axis_shape {axis_shape} != {len(devices)} devices")
    mesh_devices = np.asarray(devices).reshape(axis_shape)
    return Mesh(mesh_devices, axis_names)
