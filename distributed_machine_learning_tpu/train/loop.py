"""Training/eval drivers with the reference's measurement protocol.

Mirrors ``train_model``/``test_model`` (``part1/main.py:19-77`` and the
clones in 2a/2b/part3): hard cap at 40 iterations, per-iteration wall
clock with iteration 0 excluded (where XLA compilation lands, replacing
the reference's warm-up), loss printed every 20 iterations, and the same
total/average summary lines.  Timing brackets ``block_until_ready`` —
JAX dispatch is async, so without the block the clock would measure
enqueue latency, not the step.
"""

from __future__ import annotations

import time
from typing import Iterable

import jax
import numpy as np

from distributed_machine_learning_tpu.telemetry import get_telemetry
from distributed_machine_learning_tpu.train.state import TrainState
from distributed_machine_learning_tpu.utils.logging import rank0_print
from distributed_machine_learning_tpu.utils.timing import IterationTimer

# Reference constants (part1/main.py:32-33, 49-50).
MAX_ITERS = 40
LOSS_PRINT_EVERY = 20


def _host_local_losses(loss) -> list[tuple[int, float]]:
    """(global device index, loss) pairs addressable on this host.

    The local-loss vector (``make_train_step(local_loss=True)``) is
    sharded P(batch): on a multi-host run ``np.asarray`` on the global
    array would raise (not fully addressable), and this host should
    print only its own devices' losses anyway — reference semantics.
    Scalars (the pmean path, fully replicated) report as device 0.
    """
    if not getattr(loss, "ndim", 0):
        return [(0, float(loss))]
    shards = getattr(loss, "addressable_shards", None)
    if shards is None:
        return [(d, float(v)) for d, v in enumerate(np.asarray(loss))]
    out = []
    for sh in shards:
        start = sh.index[0].start or 0
        for j, v in enumerate(np.asarray(sh.data).ravel()):
            out.append((start + j, float(v)))
    return sorted(out)


def train_epoch(
    train_step,
    state: TrainState,
    batches: Iterable,
    place_batch=None,
    max_iters: int = MAX_ITERS,
    loss_print_every: int = LOSS_PRINT_EVERY,
    timer: IterationTimer | None = None,
    metrics=None,
    stop=None,
    watchdog=None,
    events=None,
    until_step: int | None = None,
    telemetry=None,
) -> tuple[TrainState, IterationTimer]:
    """One epoch, reference-style: returns (state, timer).

    `place_batch(images, labels)` moves a host batch onto device(s)
    (e.g. `shard_batch(mesh, ...)`); defaults to identity (jit handles
    transfer for the single-device path).

    ``stop``: optional zero-arg predicate polled at every step boundary
    (e.g. a ``runtime/resilience.PreemptionHandler``) — True ends the
    epoch cleanly with state consistent, so the caller can checkpoint.
    ``watchdog``: optional ``runtime/resilience.Watchdog``; beaten once
    per completed step, and once BEFORE the first batch is pulled — a
    loader that hangs on batch 0 is then caught as a stall with a full
    timeout window instead of hanging forever against a window already
    spent on setup/compile.
    ``events``: optional ``runtime/faults.FaultEvents``; counts steps the
    non-finite-gradient guard skipped (step counter unchanged after a
    consumed batch) and dynamic loss-scale adjustments.
    ``until_step``: optional absolute step-counter target — the epoch
    ends once ``state.step`` reaches it.  Unlike ``max_iters`` (a batch
    cap) this counts *applied* updates, so guard-skipped steps are
    retried with further batches — the supervisor's contract that a
    faulted run still lands on the same final step count.
    ``telemetry``: optional ``telemetry.Telemetry``; defaults to the
    process-wide install (``get_telemetry()``, None unless a CLI set
    ``--telemetry-dir``).  When active, the old single timing bracket is
    split into per-phase spans — ``data_wait`` / ``place_batch`` /
    ``step_dispatch`` / ``device_block`` — streamed to the Chrome trace,
    and each step logs an attempt-tagged metrics row (examples/s,
    tokens/s, MFU when the CLI installed a FLOPs model).  When None
    (the default) every telemetry branch is a single pointer test: no
    allocations, no clock reads, no syscalls beyond today's loop.
    """
    timer = timer or IterationTimer(skip_first=1)
    tel = telemetry if telemetry is not None else get_telemetry()
    if watchdog is not None:
        watchdog.beat()
    batches = iter(batches)
    batch_idx = 0
    while True:
        t_fetch = time.perf_counter() if tel is not None else 0.0
        try:
            images, labels = next(batches)
        except StopIteration:
            break
        t_got = time.perf_counter() if tel is not None else 0.0
        if batch_idx == max_iters:  # part1/main.py:32-33
            break
        if stop is not None and stop():
            rank0_print(
                f"stop requested; ending epoch after {batch_idx} iterations"
            )
            break
        if events is not None:
            step_before = int(jax.device_get(state.step))
            # Read the value NOW: the jitted step donates its input
            # state, so this buffer is dead after the call.
            scale_before = getattr(state, "loss_scale", None)
            if scale_before is not None:
                scale_before = float(scale_before)
        if tel is not None:
            # Batch geometry BEFORE placement (sharding may hide it).
            shape = getattr(images, "shape", None)
            n_examples = int(shape[0]) if shape else 0
            n_tokens = (
                int(shape[0]) * int(shape[1])
                if shape is not None and len(shape) == 2
                else None
            )
        timer.start()
        t_place = time.perf_counter() if tel is not None else 0.0
        if place_batch is not None:
            images, labels = place_batch(images, labels)
        t_dispatch = time.perf_counter() if tel is not None else 0.0
        state, loss = train_step(state, images, labels)
        t_block = time.perf_counter() if tel is not None else 0.0
        loss = jax.block_until_ready(loss)
        t_end = time.perf_counter() if tel is not None else 0.0
        iter_time = timer.stop()
        # One host sync serves both the skip accounting and the
        # until_step check below — these reads serialize dispatch, so
        # pay for them only when a consumer asked.
        step_after = (
            int(jax.device_get(state.step))
            if events is not None or until_step is not None
            else None
        )
        if events is not None:
            # Account BEFORE the watchdog beat: a RaisingWatchdog beat
            # escalates a declared stall into an exception, and a skip
            # that landed on the same step must already be counted.
            if step_after == step_before:
                events.skipped_steps += 1
            if scale_before is not None:
                before, after = scale_before, float(state.loss_scale)
                if after < before:
                    events.scaler_backoffs += 1
                elif after > before:
                    events.scaler_growths += 1
        if watchdog is not None:
            watchdog.beat()
        if tel is not None:
            step_no = (
                step_after if step_after is not None
                else int(jax.device_get(state.step))
            )
            tr = tel.tracer
            tr.complete("data_wait", t_fetch, t_got, step=batch_idx)
            if place_batch is not None:
                tr.complete("place_batch", t_place, t_dispatch,
                            step=batch_idx)
            tr.complete("step_dispatch", t_dispatch, t_block,
                        step=batch_idx)
            tr.complete("device_block", t_block, t_end, step=batch_idx)
            data_wait_s = t_got - t_fetch
            # Mirror the timer's warm-up protocol: an iteration the
            # timer excluded (XLA compile lands there) must not skew
            # the histogram quantiles either — registry p99 and the
            # printed summary percentiles describe the same population.
            # The span and the (warmup-tagged) row still record it: the
            # compile step belongs on the timeline, not in the tail.
            warmup = timer._iter <= timer.skip_first
            reg = tel.registry
            reg.counter("steps_total").inc()
            for _cname, _cval in (getattr(tel, "step_counters", None)
                                  or {}).items():
                # Static per-step increments the CLI registered (e.g.
                # ring_wire_bytes — the compressed ring's per-step wire
                # bytes, a compile-time constant of the program).  A
                # list value is labeled sub-counters:
                # [({"axis": "outer"}, bytes), ...] increments one
                # counter per label set under the shared name.
                if isinstance(_cval, (list, tuple)):
                    for _clabels, _v in _cval:
                        reg.counter(_cname, **_clabels).inc(_v)
                else:
                    reg.counter(_cname).inc(_cval)
            if not warmup:
                reg.histogram("step_seconds").observe(iter_time)
                reg.histogram("data_wait_seconds").observe(data_wait_s)
            wall = iter_time + data_wait_s
            examples_per_s = n_examples / wall if wall > 0 else 0.0
            row = {
                "batch": batch_idx,
                "iter_s": iter_time,
                "data_wait_s": data_wait_s,
                **({"warmup": True} if warmup else {}),
                "place_s": t_dispatch - t_place,
                "dispatch_s": t_block - t_dispatch,
                "block_s": t_end - t_block,
                "examples_per_s": examples_per_s,
            }
            # Overlap-aware sharded updates (zero1/fsdp overlap=True)
            # expose the consume-phase gather span: dispatch → observed
            # ready, closed at the NEXT step's consume, so row k
            # reports step k−1's gather.  On the trace timeline the
            # param_gather span overlaps data_wait — the 2004.13336
            # proof that the weight-update gather left the critical
            # path (device_block shrinks by what param_gather hides).
            pop_gather = getattr(train_step, "pop_gather_seconds", None)
            if pop_gather is not None:
                gather_s = pop_gather()
                if gather_s is not None:
                    row["param_gather_s"] = gather_s
                    if not warmup:
                        reg.histogram("param_gather_seconds").observe(
                            gather_s)
            if n_tokens is not None:
                tokens_per_s = n_tokens / wall if wall > 0 else 0.0
                row["tokens_per_s"] = tokens_per_s
                reg.gauge("tokens_per_s").set(tokens_per_s)
            else:
                tokens_per_s = None
            reg.gauge("examples_per_s").set(examples_per_s)
            mfu_val = tel.mfu_of(examples_per_s, tokens_per_s)
            if mfu_val is not None:
                row["mfu"] = mfu_val
                reg.gauge("mfu").set(mfu_val)
            tel.log_step(step_no, **row)
        if metrics is not None:
            metrics.log(
                step=int(state.step),
                loss=float(np.mean(
                    [lv for _, lv in _host_local_losses(loss)]
                )),
                iter_seconds=iter_time,
            )
        if (batch_idx + 1) % loss_print_every == 0:  # part1/main.py:49-50
            if getattr(loss, "ndim", 0):
                # local-loss mode (make_train_step(local_loss=True)): one
                # line per THIS-HOST device — the reference's every-rank-
                # prints-its-own-loss surface (part2/2a/main.py:58-61);
                # printed unconditionally (not rank-0-gated) for the same
                # reason.
                for d, lv in _host_local_losses(loss):
                    print(
                        f"Loss at {batch_idx + 1}th batch is {lv} "
                        f"(device {d})"
                    )
            else:
                rank0_print(
                    f"Loss at {batch_idx + 1}th batch is {float(loss)}"
                )
        if until_step is not None and step_after >= until_step:
            break
        batch_idx += 1
    rank0_print(timer.summary())  # part1/main.py:57-58
    return state, timer


def evaluate_lm(eval_step, params, batches: Iterable) -> tuple[float, float]:
    """Corpus-level LM eval: pooled mean NLL/token and perplexity.

    ``eval_step`` from ``train/lm_step.py::make_lm_eval_step``; batches
    yield host ``(tokens, targets)`` pairs.  Pools nll *sums* and token
    counts so unequal batch sizes still give the exact corpus mean
    (unlike the reference's mean-of-batch-means — ``part1/main.py:74``,
    which this deliberately improves on for the LM path).
    """
    import math

    total_nll = 0.0
    total_tokens = 0
    for tokens, targets in batches:
        nll, count = eval_step(params, tokens, targets)
        total_nll += float(nll)
        total_tokens += int(count)
    mean_nll = total_nll / max(total_tokens, 1)
    ppl = math.exp(min(mean_nll, 700.0))  # overflow guard for garbage models
    rank0_print(
        f"Eval: nll/token {mean_nll:.4f}, perplexity {ppl:.2f} "
        f"({total_tokens} tokens)"
    )
    return mean_nll, ppl


def evaluate(
    eval_step,
    state: TrainState,
    batches: Iterable,
    num_test_samples: int | None = None,
) -> tuple[float, float]:
    """Full-test-set eval, ``test_model`` parity (``part1/main.py:62-77``):
    test_loss = mean of per-batch mean losses; top-1 accuracy over the set.
    Every reference rank evaluates the full test set independently; here a
    single device does (params are replicated — same result by construction).
    """
    total_loss = 0.0
    correct = 0
    total = 0
    num_batches = 0
    for images, labels in batches:
        loss, c = eval_step(state.params, state.batch_stats, images, labels)
        total_loss += float(loss)
        correct += int(c)
        total += len(labels)
        num_batches += 1
    avg_loss = total_loss / max(num_batches, 1)
    if num_test_samples is not None:
        total = num_test_samples
    accuracy = 100.0 * correct / max(total, 1)
    rank0_print(
        "Test set: Average loss: {:.4f}, Accuracy: {}/{} ({:.0f}%)\n".format(
            avg_loss, correct, total, accuracy
        )
    )
    return avg_loss, accuracy
