"""SGD with momentum + weight decay, torch-update semantics.

The reference uses ``optim.SGD(lr=0.1, momentum=0.9, weight_decay=1e-4)``
(``part1/main.py:120-121``, ``part2/2a/main.py:181-182``,
``part3/main.py:138-139``).  torch's update rule (non-Nesterov) is:

    g   = grad + weight_decay * param          # decoupled-from-nothing: L2 into grad
    buf = momentum * buf + g                   # first step: buf = g
    param -= lr * buf

Note this differs from some textbook variants (no dampening, no lr inside
the momentum buffer).  Initializing the buffer to zeros makes the first
step come out to ``buf = g`` exactly, matching torch's lazy buffer init.

Implemented as a pure (state, grads) -> (state, new_params) transform so it
lives happily inside a jitted/shard_mapped train step.  An equivalent optax
chain would be ``chain(add_decayed_weights(wd), trace(decay=m), scale(-lr))``;
we keep the explicit form so the update rule is auditable against the
reference and usable as a fusion target for a Pallas kernel.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class SGDConfig:
    # Reference hyperparameters (part1/main.py:120-121); replicate exactly.
    learning_rate: float = 0.1
    momentum: float = 0.9
    weight_decay: float = 1e-4
    # Momentum-buffer STORAGE dtype name ("bfloat16"), or None for the
    # parameter dtype.  Optimizer-state memory is the difference between
    # fitting and not at realistic LM width on one chip (the buffer is a
    # full parameter-sized f32 tree); the update math still runs in f32
    # and only the carried buffer narrows — a standard mixed-precision
    # optimizer-state trade (slightly lossy accumulation, opt-in).
    momentum_dtype: str | None = None


def _momentum_dtype(config, param):
    return jnp.dtype(config.momentum_dtype) if config.momentum_dtype \
        else param.dtype


def sgd_init(params, config: SGDConfig | None = None):
    """Momentum buffers, zero-initialized (torch lazily inits to the first
    gradient; zeros + the update rule below produce the identical result).
    ``config.momentum_dtype`` narrows the stored buffer.  (getattr: LARS
    shares this init; LARSConfig rejects a set momentum_dtype at
    construction — lars.py — so the narrow path never reaches it.)
    """
    dtype_name = getattr(config, "momentum_dtype", None)
    if dtype_name is None:
        return jax.tree_util.tree_map(jnp.zeros_like, params)
    dt = jnp.dtype(dtype_name)
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, dt), params
    )


def apply_update(update, params, momentum_buf, grads):
    """Map a per-leaf ``(p, m, g) -> (new_p, new_m)`` rule over the trees
    and unzip the pairs — shared by every optimizer (sgd, lars)."""
    flat = jax.tree_util.tree_map(update, params, momentum_buf, grads)
    is_pair = lambda x: isinstance(x, tuple)
    new_params = jax.tree_util.tree_map(lambda pm: pm[0], flat, is_leaf=is_pair)
    new_momentum = jax.tree_util.tree_map(lambda pm: pm[1], flat, is_leaf=is_pair)
    return new_params, new_momentum


def sgd_update(params, momentum_buf, grads, config: SGDConfig, lr=None,
               step=None):
    """One SGD step; returns (new_params, new_momentum_buf).

    ``lr``: optional traced scalar overriding ``config.learning_rate`` —
    how a schedule (``train/schedule.py``) feeds a per-step rate into the
    jitted update without retracing (the config value is static).
    ``step`` is accepted for signature uniformity with AdamW (which needs
    it for bias correction) and ignored.
    """
    del step
    lr = config.learning_rate if lr is None else lr

    def _update(p, m, g):
        g = g + config.weight_decay * p
        # Math in the gradient dtype (f32 on the training paths); only
        # the CARRIED buffer narrows under momentum_dtype.
        m_new = config.momentum * m.astype(g.dtype) + g
        p = p - lr * m_new
        return p, m_new.astype(_momentum_dtype(config, p))

    return apply_update(_update, params, momentum_buf, grads)
