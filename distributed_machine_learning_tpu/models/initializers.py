"""Parameter initializers matching torch's default distributions.

The reference never sets custom inits, so its weights come from torch's
defaults (``nn.Conv2d``/``nn.Linear``): Kaiming-uniform with a=sqrt(5) on
the weight — which works out to U(-1/sqrt(fan_in), 1/sqrt(fan_in)) — and
U(-1/sqrt(fan_in), 1/sqrt(fan_in)) on the bias.  Flax's defaults
(lecun_normal / zeros-bias) have different variance; since the reference's
seed-69143 determinism story depends on every rank drawing identical
initial weights (``part2/2a/main.py:199``, SURVEY.md §2.5), we match the
*distribution* (bitwise identity across frameworks is impossible — RNGs
differ) and keep cross-rank identity by construction: params are initialized
once from a shared PRNGKey and replicated.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


def _fan_in(shape, is_conv: bool) -> int:
    if is_conv:
        # Flax conv kernel shape: (H, W, in_ch, out_ch)
        receptive = int(np.prod(shape[:-2]))
        return receptive * shape[-2]
    # Dense kernel shape: (in, out)
    return shape[0]


def torch_kernel_init(key, shape, dtype=jnp.float32):
    """torch's kaiming_uniform_(a=sqrt(5)) == U(-1/sqrt(fan_in), 1/sqrt(fan_in))."""
    bound = 1.0 / np.sqrt(_fan_in(shape, is_conv=len(shape) > 2))
    return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)


def make_torch_bias_init(fan_in: int):
    """torch bias init: U(-1/sqrt(fan_in), 1/sqrt(fan_in)) with the *weight's* fan-in."""
    bound = 1.0 / np.sqrt(fan_in)

    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)

    return init
