# dmlcheck-virtual-path: tests/test_fixture.py
"""DML008 firing case: unbounded subprocess in a test — a hung child
eats the whole tier-1 870s budget."""
import subprocess
import sys


def test_tool_runs(tmp_path):
    res = subprocess.run(
        [sys.executable, "tools/ckpt_verify.py", str(tmp_path)],
        capture_output=True, text=True,
    )
    assert res.returncode in (0, 2)
