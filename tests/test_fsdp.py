"""ZeRO-3/FSDP sharded data parallelism: numerical equivalence vs the
replicated-DP baseline, shard-size accounting, and multi-step stability.

The FSDP step (all-gather params → backward → reduce-scatter grads →
local shard update) must produce the same updates as replicated DP with
mean reduction (part3/DDP semantics) — same math, different placement.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.models.vgg import VGGTest
from distributed_machine_learning_tpu.parallel.fsdp import (
    fsdp_memory_footprint,
    gather_fsdp_params,
    make_fsdp_train_step,
    shard_fsdp_state,
)
from distributed_machine_learning_tpu.parallel.strategies import get_strategy
from distributed_machine_learning_tpu.train.sgd import SGDConfig
from distributed_machine_learning_tpu.train.state import TrainState
from distributed_machine_learning_tpu.train.step import make_train_step, shard_batch

GLOBAL_BATCH = 16


def _fresh_state(model):
    variables = model.init(jax.random.PRNGKey(69143), jnp.zeros((1, 32, 32, 3)))
    params = jax.tree_util.tree_map(
        lambda x: jnp.array(x, copy=True), variables["params"]
    )
    return TrainState.create(
        params=params,
        batch_stats=variables.get("batch_stats"),
        rng=jax.random.PRNGKey(7),
        config=SGDConfig(),
    )


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (GLOBAL_BATCH, 32, 32, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, (GLOBAL_BATCH,)).astype(np.int32)
    return images, labels


def test_fsdp_shards_are_one_nth(mesh8):
    state = _fresh_state(VGGTest())
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(state.params))
    fsdp_state, _, n_elems = shard_fsdp_state(state, mesh8)
    assert n_elems == n_params
    padded = fsdp_state.param_shards.shape[0]
    assert padded % 8 == 0 and padded >= n_elems
    # Each device materializes exactly 1/8 of the padded flat vector.
    for shard in fsdp_state.param_shards.addressable_shards:
        assert shard.data.shape == (padded // 8,)
    for shard in fsdp_state.momentum_shards.addressable_shards:
        assert shard.data.shape == (padded // 8,)


@pytest.mark.parametrize(
    "use_bn", [False, pytest.param(True, marks=pytest.mark.slow)]
)
def test_fsdp_matches_replicated_dp(batch, mesh8, use_bn):
    images, labels = batch
    model = VGGTest(use_bn=use_bn)

    # Replicated DP, mean semantics (part3): the baseline.
    rep_state = _fresh_state(model)
    rep_step = make_train_step(
        model, get_strategy("all_reduce", mean=True), mesh=mesh8, augment=False
    )
    x, y = shard_batch(mesh8, images, labels)
    rep_state, rep_loss = rep_step(rep_state, x, y)
    rep_state, rep_loss2 = rep_step(rep_state, x, y)

    # FSDP on the same data.
    fsdp_state, unravel, n_elems = shard_fsdp_state(_fresh_state(model), mesh8)
    step = make_fsdp_train_step(model, mesh8, unravel, n_elems, augment=False)
    fsdp_state, loss = step(fsdp_state, x, y)
    fsdp_state, loss2 = step(fsdp_state, x, y)

    np.testing.assert_allclose(float(loss), float(rep_loss), rtol=1e-5)
    np.testing.assert_allclose(float(loss2), float(rep_loss2), rtol=1e-4)
    got = gather_fsdp_params(fsdp_state, unravel, n_elems)
    for la, lb in zip(
        jax.tree_util.tree_leaves(got),
        jax.tree_util.tree_leaves(rep_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb), rtol=1e-4, atol=1e-5
        )
    # BN running stats follow the same axis-synced update in both steps.
    for la, lb in zip(
        jax.tree_util.tree_leaves(fsdp_state.batch_stats),
        jax.tree_util.tree_leaves(rep_state.batch_stats),
    ):
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb), rtol=1e-4, atol=1e-5
        )


def test_fsdp_state_roundtrip(mesh8):
    state = _fresh_state(VGGTest())
    fsdp_state, unravel, n_elems = shard_fsdp_state(state, mesh8)
    got = gather_fsdp_params(fsdp_state, unravel, n_elems)
    for la, lb in zip(
        jax.tree_util.tree_leaves(got), jax.tree_util.tree_leaves(state.params)
    ):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_fsdp_overlap_bit_identical_to_sync(batch, mesh8):
    """ISSUE-9 parity acceptance for ZeRO-3: the prefetch-protocol
    build (pre-gathered full vector consumed by the update program, the
    gather dispatched behind the previous step's data wait) must be
    bitwise equal to the sync build — the gather is pure data movement
    and the update math is shared."""
    images, labels = batch
    mx, my = shard_batch(mesh8, images, labels)
    model = VGGTest()

    def run(overlap):
        st, unravel, n_elems = shard_fsdp_state(_fresh_state(model), mesh8)
        step = make_fsdp_train_step(model, mesh8, unravel, n_elems,
                                    augment=False, overlap=overlap)
        losses = []
        for _ in range(3):
            st, loss = step(st, mx, my)
            losses.append(float(loss))
        return st, losses, unravel, n_elems

    sync, sync_losses, unravel, n_elems = run(False)
    ov, ov_losses, _, _ = run(True)
    assert sync_losses == ov_losses
    np.testing.assert_array_equal(
        np.asarray(sync.param_shards), np.asarray(ov.param_shards)
    )
    np.testing.assert_array_equal(
        np.asarray(sync.momentum_shards), np.asarray(ov.momentum_shards)
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(
            gather_fsdp_params(sync, unravel, n_elems)),
        jax.tree_util.tree_leaves(
            gather_fsdp_params(ov, unravel, n_elems)),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fsdp_overlap_prefetch_miss_recovers(batch, mesh8):
    """The prefetch holder keys on the state's param_shards identity:
    after a rebind (a checkpoint restore rebuilds the state object),
    the wrapper must detect the miss, re-gather, and keep the
    trajectory — not consume a stale full vector."""
    images, labels = batch
    mx, my = shard_batch(mesh8, images, labels)
    model = VGGTest()

    st, unravel, n_elems = shard_fsdp_state(_fresh_state(model), mesh8)
    step = make_fsdp_train_step(model, mesh8, unravel, n_elems,
                                augment=False, overlap=True)
    st, _ = step(st, mx, my)
    st, _ = step(st, mx, my)
    # Simulate a restore: same values, NEW array objects.
    rebound = st.replace(
        param_shards=jnp.array(st.param_shards, copy=True),
        momentum_shards=jax.tree_util.tree_map(
            lambda a: jnp.array(a, copy=True), st.momentum_shards
        ),
    )
    st3, _ = step(rebound, mx, my)

    ref_state, ref_unravel, ref_n = shard_fsdp_state(
        _fresh_state(model), mesh8)
    ref_step = make_fsdp_train_step(model, mesh8, ref_unravel, ref_n,
                                    augment=False, overlap=False)
    for _ in range(3):
        ref_state, _ = ref_step(ref_state, mx, my)
    np.testing.assert_array_equal(
        np.asarray(st3.param_shards), np.asarray(ref_state.param_shards)
    )


@pytest.mark.slow
def test_fsdp_lm_overlap_bit_identical_to_sync(mesh8):
    """The LM flavor of the prefetch protocol (what the CLI's
    ``--parallel fsdp --overlap-update`` builds) keeps the bitwise
    guarantee too — AdamW moments included."""
    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.parallel.fsdp import (
        make_fsdp_lm_train_step,
    )
    from distributed_machine_learning_tpu.train.adamw import AdamWConfig
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state

    model = TransformerLM(vocab_size=64, d_model=32, n_layers=2,
                          n_heads=4, attn_impl="dense")
    rng = np.random.default_rng(3)
    toks = rng.integers(0, 64, (16, 17))
    from distributed_machine_learning_tpu.train.step import shard_batch

    mx, my = shard_batch(mesh8, toks[:, :-1].astype(np.int32),
                         toks[:, 1:].astype(np.int32))

    def run(overlap):
        st, unravel, n_elems = shard_fsdp_state(
            init_lm_state(model, seed=0, config=AdamWConfig()), mesh8)
        step = make_fsdp_lm_train_step(model, mesh8, unravel, n_elems,
                                       overlap=overlap)
        for _ in range(3):
            st, loss = step(st, mx, my)
        return st, float(loss)

    sync, sync_loss = run(False)
    ov, ov_loss = run(True)
    assert sync_loss == ov_loss
    np.testing.assert_array_equal(
        np.asarray(sync.param_shards), np.asarray(ov.param_shards))
    for a, b in zip(jax.tree_util.tree_leaves(sync.momentum_shards),
                    jax.tree_util.tree_leaves(ov.momentum_shards)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fsdp_memory_footprint():
    fp = fsdp_memory_footprint(9_231_114, 8)
    assert fp["fsdp"] * 7 < fp["replicated"]  # ~8x smaller (padding slack)
    fp1 = fsdp_memory_footprint(100, 1)
    assert fp1["fsdp"] == fp1["replicated"]
