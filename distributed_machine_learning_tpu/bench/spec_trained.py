"""Trained-draft speculative serving bench — the acceptance-real numbers.

`bench_lm.py --decode --spec-gamma` measures the random-draft FLOOR
(acceptance ≈ 0); this bench completes the envelope with a REAL target
+ draft pair (train via ``cli.lm --ckpt-dir``, distill the draft via
``cli.distill`` — one command each), serving prompts drawn from the
same corpus:

- vanilla greedy vs speculative γ ∈ {4, 6}, batch 1 AND batch 8
  (eight DIFFERENT corpus prompts riding per-row frontiers — the
  batched-speculation headline row, VERDICT r4 item 1);
- one sampled-acceptance point (temperature/top-p warps active in the
  Leviathan rule) vs plain sampled decoding — VERDICT r4 item 3's
  measured companion to the distributional tests.

Timing: the decode bench's two-point method — per-token time is the
slope between two generation lengths (32 vs --gen-tokens), each timed
with chained dispatches + one fetch (cancels the tunnel RTT).

Reproduce end-to-end::

    python -m distributed_machine_learning_tpu.cli.lm --parallel dp \
        --data-dir <corpus> --d-model 2048 --n-layers 8 --n-heads 16 \
        --n-kv-heads 4 --seq-len 512 --batch-size 8 --max-iters 500 \
        --compute-dtype bfloat16 --ckpt-dir <target>
    python -m distributed_machine_learning_tpu.cli.distill \
        --target-ckpt-dir <target> --d-model 2048 --n-layers 8 \
        --n-heads 16 --n-kv-heads 4 --draft-d-model 128 \
        --draft-n-layers 2 --data-dir <corpus> --seq-len 512 \
        --batch-size 8 --max-iters 1500 --ckpt-dir <draft>
    python -m distributed_machine_learning_tpu.bench.spec_trained \
        --target-ckpt-dir <target> --draft-ckpt-dir <draft> \
        --data-dir <corpus>
"""

from __future__ import annotations

import argparse
import json

import jax
import jax.numpy as jnp
import numpy as np


def _prompts(data_dir: str, batch: int, prompt_len: int):
    """[batch, prompt_len] byte windows from the corpus, BOS-led, at
    deterministic spread-out offsets — real text, distinct rows."""
    from distributed_machine_learning_tpu.data.text import BOS, load_corpus

    corpus = load_corpus(data_dir)
    span = len(corpus) - prompt_len - 1
    if span < batch:
        # Distinct rows are the CONTRACT: identical prompts would make
        # the per-row frontiers move in lockstep and overstate batched
        # acceptance.
        raise ValueError(
            f"corpus ({len(corpus)} tokens) too small for {batch} "
            f"distinct {prompt_len}-token prompts"
        )
    rows = []
    for b in range(batch):
        off = (b * 7919) % span
        rows.append(
            np.concatenate([[BOS], corpus[off:off + prompt_len - 1]])
        )
    return jnp.asarray(np.stack(rows), jnp.int32)


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--target-ckpt-dir", dest="target_ckpt_dir",
                   required=True)
    p.add_argument("--draft-ckpt-dir", dest="draft_ckpt_dir", required=True)
    p.add_argument("--data-dir", dest="data_dir", required=True)
    p.add_argument("--d-model", dest="d_model", default=2048, type=int)
    p.add_argument("--n-layers", dest="n_layers", default=8, type=int)
    p.add_argument("--n-heads", dest="n_heads", default=16, type=int)
    p.add_argument("--n-kv-heads", dest="n_kv_heads", default=4, type=int)
    p.add_argument("--draft-d-model", dest="draft_d_model", default=128,
                   type=int)
    p.add_argument("--draft-n-layers", dest="draft_n_layers", default=2,
                   type=int)
    p.add_argument("--draft-n-heads", dest="draft_n_heads", default=8,
                   type=int)
    p.add_argument("--prompt-len", dest="prompt_len", default=512, type=int)
    p.add_argument("--gen-tokens", dest="gen_tokens", default=160, type=int)
    p.add_argument("--gammas", default="4,6")
    p.add_argument("--batches", default="1,8")
    p.add_argument("--reps", default=3, type=int)
    p.add_argument("--chain", default=4, type=int)
    p.add_argument("--quant", action="store_true",
                   help="serve the TARGET weight-only int8 (the draft "
                        "stays bf16 — it is small and runs the most "
                        "steps per round, latency-bound not weight-"
                        "bound); composes with batched speculation")
    p.add_argument("--kv-cache-dtype", dest="kv_cache_dtype", default=None,
                   help="decode cache storage dtype for BOTH models")
    args = p.parse_args()

    from distributed_machine_learning_tpu.bench.harness import (
        cast_serving_params,
        length_slope_fit,
        prepare_serving_params,
        two_point_dispatch,
    )

    from distributed_machine_learning_tpu.cli.generate import (
        _restore_lm_params,
    )
    from distributed_machine_learning_tpu.data.text import VOCAB_SIZE
    from distributed_machine_learning_tpu.inference.generate import (
        make_generate_fn,
    )
    from distributed_machine_learning_tpu.inference.speculative import (
        make_speculative_generate_fn,
    )
    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )

    kv_dtype = (
        jnp.dtype(args.kv_cache_dtype) if args.kv_cache_dtype else None
    )
    target = TransformerLM(
        vocab_size=VOCAB_SIZE, d_model=args.d_model,
        n_layers=args.n_layers, n_heads=args.n_heads,
        n_kv_heads=args.n_kv_heads, compute_dtype=jnp.bfloat16,
        kv_cache_dtype=kv_dtype,
    )
    draft = TransformerLM(
        vocab_size=VOCAB_SIZE, d_model=args.draft_d_model,
        n_layers=args.draft_n_layers, n_heads=args.draft_n_heads,
        compute_dtype=jnp.bfloat16, kv_cache_dtype=kv_dtype,
    )
    quant = "int8" if args.quant else None
    tparams = prepare_serving_params(
        _restore_lm_params(args.target_ckpt_dir, args.n_layers), quant
    )
    dparams = cast_serving_params(
        _restore_lm_params(args.draft_ckpt_dir, args.draft_n_layers),
        jnp.bfloat16,
    )
    key = jax.random.PRNGKey(0)
    n_small = 32

    def slope(make_fn, prompt):
        def timed_for(n_tokens):
            fn = make_fn(n_tokens)
            jax.block_until_ready(fn(prompt, key))
            return two_point_dispatch(
                lambda: fn(prompt, key),
                lambda out: np.asarray(out[0, -1]),
                args.reps, args.chain,
            )

        # length_slope_fit validates n_small < gen_tokens and guards
        # the jitter cases (bench/harness.py — one fit, every bench).
        return length_slope_fit(timed_for, n_small, args.gen_tokens)

    # Each factory call builds ONE jitted program per length; the inner
    # lambda only binds params (a fresh make_* per dispatch would
    # retrace every call — the first cut of this bench did exactly
    # that and read compile-cache jitter as negative slopes).
    def vanilla_fn(n, **warp):
        g = make_generate_fn(target, n, quantize=quant, **warp)
        return lambda pr, k: g(tparams, pr, k)

    def spec_fn(n, gamma, **warp):
        g = make_speculative_generate_fn(target, draft, n, gamma=gamma,
                                         quantize=quant, **warp)
        return lambda pr, k: g(tparams, dparams, pr, k)

    for batch in (int(b) for b in args.batches.split(",")):
        prompt = _prompts(args.data_dir, batch, args.prompt_len)
        t_van = slope(vanilla_fn, prompt)
        print(json.dumps({
            "metric": "spec_trained_vanilla_tokens_per_sec",
            "value": round(batch / t_van, 1), "batch": batch,
            "quant": quant, "kv_cache_dtype": args.kv_cache_dtype,
            "per_sequence_tokens_per_sec": round(1 / t_van, 1),
            "ms_per_step": round(t_van * 1e3, 3),
        }), flush=True)
        for gamma in (int(g) for g in args.gammas.split(",")):
            t_spec = slope(
                lambda n, g=gamma: spec_fn(n, g), prompt
            )
            print(json.dumps({
                "metric": "spec_trained_tokens_per_sec",
                "value": round(batch / t_spec, 1), "batch": batch,
                "gamma": gamma, "quant": quant,
                "kv_cache_dtype": args.kv_cache_dtype,
                "per_sequence_tokens_per_sec": round(1 / t_spec, 1),
                "vs_vanilla": round(t_van / t_spec, 3),
            }), flush=True)

    # Sampled-acceptance point: the Leviathan accept/resample rule under
    # real warps, vs plain sampled decoding (batch 1).
    prompt = _prompts(args.data_dir, 1, args.prompt_len)
    warp = dict(temperature=0.8, top_p=0.9)
    t_plain = slope(lambda n: vanilla_fn(n, **warp), prompt)
    t_spec = slope(lambda n: spec_fn(n, 4, **warp), prompt)
    print(json.dumps({
        "metric": "spec_trained_sampled_tokens_per_sec",
        "value": round(1 / t_spec, 1), "gamma": 4, "quant": quant,
        "kv_cache_dtype": args.kv_cache_dtype, **warp,
        "plain_sampled_tokens_per_sec": round(1 / t_plain, 1),
        "vs_plain_sampled": round(t_plain / t_spec, 3),
    }), flush=True)


if __name__ == "__main__":
    main()
