"""The hand-rolled ppermute ring vs lax.psum/pmean (SURVEY.md §4d):
property tests on an 8-device CPU mesh — plus the round-7 compressed
ring (int8/topk wire schemes, error-feedback residuals, wire-byte
accounting and the slow acceptance audit)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from conftest import shard_map_compat as shard_map

from distributed_machine_learning_tpu.ops.ring import (
    get_wire_scheme,
    ring_all_reduce,
    ring_all_reduce_flat,
    ring_wire_bytes,
)


def _run_on_mesh(mesh, fn, per_device_inputs):
    """shard_map a per-device fn over stacked inputs (leading axis = device)."""
    wrapped = shard_map(
        fn, mesh=mesh, in_specs=P("batch"), out_specs=P("batch"), check_vma=False
    )
    return jax.jit(wrapped)(per_device_inputs)


@pytest.mark.parametrize("length", [1, 7, 8, 64, 1000, 4097])
@pytest.mark.parametrize("mean", [False, True])
def test_ring_flat_matches_psum(mesh8, length, mean, rng):
    n = 8
    data = rng.standard_normal((n, length)).astype(np.float32)
    expected = data.sum(axis=0) / (n if mean else 1)

    def per_device(x):
        x = x.reshape(-1)  # shard has leading dim 1
        out = ring_all_reduce_flat(x, "batch", n, mean=mean)
        return out[None]

    result = _run_on_mesh(mesh8, per_device, jnp.asarray(data))
    # Every device must hold the same full reduction.
    for d in range(n):
        np.testing.assert_allclose(
            np.asarray(result[d]), expected, rtol=1e-5, atol=1e-5
        )


@pytest.mark.parametrize("bucket_bytes", [64, 1024, 25 * 2**20])
def test_ring_pytree_bucketing(mesh8, bucket_bytes, rng):
    n = 8
    tree_shapes = {"w": (33, 17), "b": (129,), "k": (3, 3, 4, 8)}
    data = {
        k: rng.standard_normal((n, *s)).astype(np.float32)
        for k, s in tree_shapes.items()
    }

    def per_device(tree):
        local = jax.tree_util.tree_map(lambda x: x[0], tree)
        out = ring_all_reduce(
            local, "batch", n, mean=True, bucket_bytes=bucket_bytes
        )
        return jax.tree_util.tree_map(lambda x: x[None], out)

    wrapped = shard_map(
        per_device, mesh=mesh8, in_specs=P("batch"), out_specs=P("batch"),
        check_vma=False,
    )
    result = jax.jit(wrapped)(jax.tree_util.tree_map(jnp.asarray, data))
    for k in tree_shapes:
        expected = data[k].sum(axis=0) / n
        for d in range(n):
            np.testing.assert_allclose(
                np.asarray(result[k][d]), expected, rtol=1e-5, atol=1e-5
            )


def test_ring_matches_pmean_collective(mesh4, rng):
    """Direct head-to-head vs lax.pmean on the same mesh (world size 4 —
    the reference cluster size)."""
    n = 4
    data = rng.standard_normal((n, 513)).astype(np.float32)

    def per_device(x):
        x = x.reshape(-1)
        ours = ring_all_reduce_flat(x, "batch", n, mean=True)
        theirs = lax.pmean(x, "batch")
        return (ours - theirs)[None]

    diff = _run_on_mesh(mesh4, per_device, jnp.asarray(data))
    np.testing.assert_allclose(np.asarray(diff), 0.0, atol=1e-6)


def test_ring_single_device_identity():
    x = jnp.arange(10.0)
    assert np.allclose(ring_all_reduce_flat(x, "batch", 1), x)


# ---------------------------------------------------------------------------
# Compressed ring (round 7): int8 / topk wire schemes.
# ---------------------------------------------------------------------------


def _reduce_compressed(n, data, scheme, mean=True, length=None):
    """Run the compressed flat ring on an n-device mesh; returns the
    [n, L] per-rank outputs."""
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    mesh = make_mesh(n)
    f = shard_map(
        lambda v: ring_all_reduce_flat(
            v.reshape(-1), "batch", n, mean=mean, scheme=scheme
        )[None],
        mesh=mesh, in_specs=P("batch"), out_specs=P("batch"),
        check_vma=False,
    )
    return np.asarray(jax.jit(f)(jnp.asarray(data)))


@pytest.mark.parametrize("world", [2, 4, 8])
@pytest.mark.parametrize("length", [64, 1000])
def test_int8_ring_close_and_rank_identical(world, length, rng):
    """Per-chunk int8+scale hops: every rank ends with IDENTICAL bits
    (encoded payloads are relayed verbatim in the gather phase), and
    the value is within accumulated per-hop quantization error of the
    exact mean."""
    data = rng.standard_normal((world, length)).astype(np.float32)
    out = _reduce_compressed(world, data, get_wire_scheme("int8"))
    for d in range(1, world):
        np.testing.assert_array_equal(out[d], out[0])
    exact = data.sum(axis=0) / world
    # Each of the ≤2(n−1) lossy encodes rounds by ≤ scale/2 = amax/254;
    # partial-sum amax is bounded by the column-sum amax.
    bound = 2 * world * np.abs(data).sum(axis=0).max() / 254 / world
    assert np.max(np.abs(out[0] - exact)) <= max(bound, 0.05)


@pytest.mark.parametrize("world", [2, 4, 8])
def test_topk_full_frac_is_exact(world, rng):
    """topk with frac=1.0 sends every element — the scatter/relay
    plumbing must then reproduce the exact ring bit-for-bit in value."""
    data = rng.standard_normal((world, 257)).astype(np.float32)
    out = _reduce_compressed(
        world, data, get_wire_scheme("topk", topk_frac=1.0)
    )
    exact = data.sum(axis=0) / world
    for d in range(world):
        np.testing.assert_allclose(out[d], exact, rtol=1e-5, atol=1e-5)


def test_topk_partial_frac_rank_identical_and_bounded(rng):
    n = 8
    data = rng.standard_normal((n, 512)).astype(np.float32)
    out = _reduce_compressed(
        n, data, get_wire_scheme("topk", topk_frac=0.25)
    )
    for d in range(1, n):
        np.testing.assert_array_equal(out[d], out[0])
    exact = data.sum(axis=0) / n
    # Sparsification drops mass but must never invent it.
    assert np.max(np.abs(out[0] - exact)) <= np.abs(data).sum(0).max() / n


@pytest.mark.parametrize("scheme_name", ["int8", "topk"])
def test_compressed_pytree_ragged_buckets(mesh8, scheme_name, rng):
    """Tiny bucket_bytes force many buckets with a ragged tail (the
    last bucket shorter than the rest, chunks padded per rank); the
    compressed pytree ring must still reduce every leaf and stay
    rank-identical."""
    n = 8
    tree_shapes = {"w": (33, 17), "b": (129,), "k": (3, 3, 4, 8)}
    data = {
        k: rng.standard_normal((n, *s)).astype(np.float32)
        for k, s in tree_shapes.items()
    }
    scheme = get_wire_scheme(scheme_name, topk_frac=1.0)

    def per_device(tree):
        local = jax.tree_util.tree_map(lambda x: x[0], tree)
        out = ring_all_reduce(
            local, "batch", n, mean=True, bucket_bytes=1024, scheme=scheme
        )
        return jax.tree_util.tree_map(lambda x: x[None], out)

    wrapped = shard_map(
        per_device, mesh=mesh8, in_specs=P("batch"), out_specs=P("batch"),
        check_vma=False,
    )
    result = jax.jit(wrapped)(jax.tree_util.tree_map(jnp.asarray, data))
    for k in tree_shapes:
        expected = data[k].sum(axis=0) / n
        for d in range(1, n):
            np.testing.assert_array_equal(
                np.asarray(result[k][d]), np.asarray(result[k][0])
            )
        tol = 0.08 if scheme_name == "int8" else 1e-5
        np.testing.assert_allclose(
            np.asarray(result[k][0]), expected, rtol=tol, atol=tol
        )


def test_ring_wire_bytes_accounting():
    """Static byte accounting: exact=4B/elem; bf16 halves; int8 is
    chunk+4 per hop (~4x); topk is 8B × k (~4x at frac=1/8) — and the
    bucketed sum covers the ragged tail bucket."""
    n, elems = 8, 10_000
    exact = ring_wire_bytes(elems, n)
    chunk = -(-elems // n)
    assert exact == 2 * (n - 1) * chunk * 4
    assert ring_wire_bytes(elems, n, scheme=get_wire_scheme("bf16")) \
        == exact // 2
    int8 = ring_wire_bytes(elems, n, scheme=get_wire_scheme("int8"))
    assert exact / int8 > 3.9
    topk = ring_wire_bytes(
        elems, n, scheme=get_wire_scheme("topk", topk_frac=0.125)
    )
    assert exact / topk > 3.9
    # Ragged buckets: 3 buckets of 1024B (256 elems) + a 192-elem tail.
    ragged = ring_wire_bytes(960, 4, bucket_bytes=1024)
    assert ragged == 2 * 3 * ((256 // 4) * 3 + (-(-192 // 4))) * 4
    # Degenerate cases.
    assert ring_wire_bytes(0, 8) == 0
    assert ring_wire_bytes(100, 1) == 0


def test_ring_residual_accounts_total_dropped_mass(mesh4, rng):
    """Complete EF bookkeeping: summed over ranks, the residuals equal
    the all-reduce's total compression error — N·(exact mean − output)
    under mean semantics.  Every dropped byte lands in exactly one
    rank's residual (per-hop send errors + the owner's broadcast gap)."""
    n, L = 4, 192
    data = rng.standard_normal((n, L)).astype(np.float32)

    def per_device(v):
        out, res = ring_all_reduce_flat(
            v.reshape(-1), "batch", n, mean=True,
            scheme=get_wire_scheme("topk", topk_frac=0.2),
            return_residual=True,
        )
        return out[None], res[None]

    f = shard_map(per_device, mesh=mesh4, in_specs=P("batch"),
                  out_specs=(P("batch"), P("batch")))
    out, res = jax.jit(f)(jnp.asarray(data))
    out, res = np.asarray(out), np.asarray(res)
    exact_mean = data.sum(axis=0) / n
    # Residuals sum to N × the output's deviation from the exact mean.
    np.testing.assert_allclose(
        res.sum(axis=0), n * (exact_mean - out[0]), rtol=1e-4, atol=1e-4
    )
    # The exact scheme's residual is identically zero.
    def per_device_exact(v):
        out, r = ring_all_reduce_flat(
            v.reshape(-1), "batch", n, mean=True, return_residual=True
        )
        return out[None], r[None]

    g = shard_map(per_device_exact, mesh=mesh4, in_specs=P("batch"),
                  out_specs=(P("batch"), P("batch")))
    _, res0 = jax.jit(g)(jnp.asarray(data))
    assert float(jnp.max(jnp.abs(res0))) == 0.0


def test_error_feedback_recovers_dropped_mass(mesh4, rng):
    """The EF acceptance property (satellite): with a PERSISTENT
    gradient direction (the same per-rank gradient every step — the
    canonical EF failure mode, where small coordinates are dropped by
    top-k on every step and never transmitted), the cumulative synced
    gradient of the topk ring WITH error feedback is closer to the
    exact ring's than without: the residual grows the dropped
    coordinates until they win a later step's top-k."""
    from distributed_machine_learning_tpu.parallel.strategies import (
        get_strategy,
    )

    n, L, steps = 4, 256, 8
    g_fixed = rng.standard_normal((n, L)).astype(np.float32)
    grads = [g_fixed for _ in range(steps)]

    def run(strategy):
        stateful = strategy.stateful

        def per_device(gs):
            # gs: [1, steps, L] — this rank's gradient sequence.
            g_seq = gs.reshape(steps, L)
            res = jnp.zeros((L,), jnp.float32)
            total = jnp.zeros((L,), jnp.float32)
            for t in range(steps):
                if stateful:
                    synced, res = strategy.apply(
                        g_seq[t], res, "batch", n
                    )
                else:
                    synced = strategy(g_seq[t], "batch", n)
                total = total + synced
            return total[None]

        f = shard_map(per_device, mesh=mesh4, in_specs=P("batch"),
                      out_specs=P("batch"), check_vma=False)
        stacked = jnp.asarray(np.stack(grads, axis=1))  # [n, steps, L]
        return np.asarray(jax.jit(f)(stacked))[0]

    exact = run(get_strategy("ring"))
    with_ef = run(get_strategy("ring", compress="topk", topk_frac=0.1))
    without = run(get_strategy("ring", compress="topk", topk_frac=0.1,
                               error_feedback=False))
    err_ef = np.linalg.norm(with_ef - exact)
    err_no = np.linalg.norm(without - exact)
    assert err_ef < err_no, (err_ef, err_no)
    # And materially so (measured ~0.65 at this fixed seed): without EF
    # the same mass is re-dropped every step and the error grows with T;
    # with EF the outstanding error stays bounded at ~one step's drop.
    assert err_ef < 0.75 * err_no, (err_ef, err_no)


def test_stateful_step_threads_residual(mesh8, rng):
    """make_train_step with an EF strategy keeps the (state, x, y) →
    (state, loss) caller signature, threads the donated residual
    internally, and the residual is per-device state that becomes
    nonzero after a compressed step."""
    from distributed_machine_learning_tpu.cli.common import (
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.models.registry import get_model
    from distributed_machine_learning_tpu.parallel.strategies import (
        get_strategy,
    )
    from distributed_machine_learning_tpu.train.sgd import SGDConfig
    from distributed_machine_learning_tpu.train.step import (
        make_train_step,
        shard_batch,
    )

    model = get_model("vggtest", use_bn=False)
    strategy = get_strategy("ring", compress="int8")
    assert strategy.stateful
    state = init_model_and_state(
        model, config=SGDConfig(learning_rate=0.1, weight_decay=0.0)
    )
    step = make_train_step(model, strategy, mesh=mesh8, augment=False)
    assert step.sync_state() is None  # lazily initialized
    for _ in range(2):
        x = rng.integers(0, 256, (32, 32, 32, 3), dtype=np.uint8)
        y = rng.integers(0, 10, 32).astype(np.int32)
        state, loss = step(state, *shard_batch(mesh8, x, y))
    assert np.isfinite(float(loss))
    res = step.sync_state()
    leaves = jax.tree_util.tree_leaves(res)
    assert leaves and leaves[0].shape[0] == 8  # [world, ...] sharded rows
    assert any(float(jnp.max(jnp.abs(l))) > 0 for l in leaves)
    # Params stayed replicated and finite through the stateful program.
    for p in jax.tree_util.tree_leaves(state.params):
        assert bool(jnp.all(jnp.isfinite(p)))
    step.reset_sync_state()
    assert step.sync_state() is None


@pytest.mark.parametrize("direction", ["shrink", "grow"])
def test_residual_world_change_resets_not_crashes(direction, mesh8, mesh4,
                                                  tmp_path, rng, capsys):
    """ISSUE 10 satellite: the EF residual is a ``[world, …]`` stacked
    buffer.  Carrying it across an elastic world change (8→4 shrink or
    4→8 grow) through ``set_sync_state`` must REBUILD it at the new
    world — logged and counted as ``ring_residual_reset`` — never shape-
    crash the compiled step; a same-world install is preserved."""
    from distributed_machine_learning_tpu.cli.common import (
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.models.registry import get_model
    from distributed_machine_learning_tpu.parallel.strategies import (
        get_strategy,
    )
    from distributed_machine_learning_tpu.telemetry import (
        Telemetry,
        set_telemetry,
    )
    from distributed_machine_learning_tpu.train.sgd import SGDConfig
    from distributed_machine_learning_tpu.train.step import (
        make_train_step,
        shard_batch,
    )

    src_mesh, dst_mesh = ((mesh8, mesh4) if direction == "shrink"
                          else (mesh4, mesh8))
    dst_world = dst_mesh.shape["batch"]
    model = get_model("vggtest", use_bn=False)
    state = init_model_and_state(
        model, config=SGDConfig(learning_rate=0.1, weight_decay=0.0)
    )

    def batch():
        x = rng.integers(0, 256, (32, 32, 32, 3), dtype=np.uint8)
        y = rng.integers(0, 10, 32).astype(np.int32)
        return x, y

    src_step = make_train_step(model, get_strategy("ring", compress="int8"),
                               mesh=src_mesh, augment=False)
    state, _ = src_step(state, *shard_batch(src_mesh, *batch()))
    carried = jax.tree_util.tree_map(jnp.copy, src_step.sync_state())

    tel = Telemetry(tmp_path / "tel")
    prev = set_telemetry(tel)
    try:
        dst_step = make_train_step(
            model, get_strategy("ring", compress="int8"), mesh=dst_mesh,
            augment=False,
        )
        dst_step.set_sync_state(carried)
        # The mismatch was detected at install time: reset to lazy-fresh.
        assert dst_step.sync_state() is None
        assert tel.registry.counter("ring_residual_reset").value == 1
        # The elastic flow restores state through reshard_restore, which
        # places it on the NEW mesh; mirror that placement here.
        from jax.sharding import NamedSharding, PartitionSpec

        state = jax.device_put(
            state, NamedSharding(dst_mesh, PartitionSpec())
        )
        state, loss = dst_step(state, *shard_batch(dst_mesh, *batch()))
        assert np.isfinite(float(loss))
        res = dst_step.sync_state()
        assert jax.tree_util.tree_leaves(res)[0].shape[0] == dst_world
        # Same-world install round-trips (no reset, values preserved).
        held = jax.tree_util.tree_map(jnp.copy, res)
        dst_step.set_sync_state(held)
        got = dst_step.sync_state()
        assert tel.registry.counter("ring_residual_reset").value == 1
        for a, b in zip(jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(held)):
            assert np.array_equal(np.asarray(a), np.asarray(b))
    finally:
        set_telemetry(prev)
        tel.close()
    assert "ring_residual_reset" in capsys.readouterr().out


def test_cli_ring_compress_flags():
    """Flag surface: --ring-compress choices parse onto the namespace,
    --ring-topk-frac is validated at parse time (before any runtime
    spin-up), and error feedback defaults on with an opt-out."""
    from distributed_machine_learning_tpu.cli.common import (
        make_flag_parser,
        parse_flags,
    )

    parser = make_flag_parser("test")
    args = parse_flags(parser, ["--ring-compress", "int8"])
    assert args.ring_compress == "int8"
    assert args.ring_error_feedback is True
    args = parse_flags(parser, ["--ring-compress", "topk",
                                "--ring-topk-frac", "0.25",
                                "--ring-no-error-feedback"])
    assert args.ring_topk_frac == 0.25
    assert args.ring_error_feedback is False
    with pytest.raises(SystemExit):
        parse_flags(parser, ["--ring-topk-frac", "0"])
    with pytest.raises(SystemExit):
        parse_flags(parser, ["--ring-compress", "fp4"])


def test_ring_strategy_compress_validation():
    from distributed_machine_learning_tpu.parallel.strategies import (
        get_strategy,
    )

    with pytest.raises(ValueError, match="compress"):
        get_strategy("ring", compress="fp4")
    with pytest.raises(ValueError, match="topk_frac"):
        get_strategy("ring", compress="topk", topk_frac=0.0)
    with pytest.warns(DeprecationWarning, match="wire_dtype"):
        s = get_strategy("ring", wire_dtype="bfloat16")
    assert s.scheme().name == "bf16"
    assert not s.stateful  # cast-only stays stateless
    assert not get_strategy(
        "ring", compress="int8", error_feedback=False
    ).stateful


@pytest.mark.slow
def test_int8_ring_acceptance_audit_and_parity(mesh8, rng):
    """The round-7 acceptance criteria, both halves:

    1. HLO wire-byte audit: the AOT-compiled part3 train step (vggtest,
       8-device mesh) moves ≥3x fewer collective-permute payload bytes
       with the int8 ring than the exact ring — read from the compiled
       executables, not the source.
    2. Fixed-seed parity: over a 40-iteration synthetic run, the
       int8+error-feedback ring's final loss is within 1% relative of
       the uncompressed ring's.
    """
    from distributed_machine_learning_tpu.bench.overlap_audit import (
        wire_bytes_from_hlo,
    )
    from distributed_machine_learning_tpu.cli.common import (
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.models.registry import get_model
    from distributed_machine_learning_tpu.parallel.strategies import (
        get_strategy,
    )
    from distributed_machine_learning_tpu.train.sgd import SGDConfig
    from distributed_machine_learning_tpu.train.step import (
        make_train_step,
        shard_batch,
    )

    model = get_model("vggtest", use_bn=False)

    def lower_hlo(strategy):
        step = make_train_step(model, strategy, mesh=mesh8, augment=False)
        state_shape = jax.eval_shape(
            lambda: init_model_and_state(
                model,
                config=SGDConfig(learning_rate=0.1, weight_decay=0.0),
            )
        )
        x = jax.ShapeDtypeStruct((32, 32, 32, 3), jnp.uint8)
        y = jax.ShapeDtypeStruct((32,), jnp.int32)
        if getattr(strategy, "stateful", False):
            res = jax.eval_shape(
                lambda: step.fresh_sync_state(state_shape.params)
            )
            return step.inner.lower(
                state_shape, x, y, res
            ).compile().as_text()
        return step.lower(state_shape, x, y).compile().as_text()

    exact_bytes = wire_bytes_from_hlo(lower_hlo(get_strategy("ring")))
    int8_bytes = wire_bytes_from_hlo(
        lower_hlo(get_strategy("ring", compress="int8"))
    )
    assert exact_bytes["count"] > 0 and int8_bytes["count"] > 0
    ratio = int8_bytes["total_bytes"] / exact_bytes["total_bytes"]
    assert ratio <= 1 / 3, (int8_bytes, exact_bytes)

    # -- half 2: fixed-seed loss parity over the 40-iter protocol ------
    batches = [
        (rng.integers(0, 256, (64, 32, 32, 3), dtype=np.uint8),
         rng.integers(0, 10, 64).astype(np.int32))
        for _ in range(40)
    ]

    def final_loss(strategy):
        state = init_model_and_state(
            model, config=SGDConfig(learning_rate=0.1, weight_decay=0.0)
        )
        step = make_train_step(model, strategy, mesh=mesh8, augment=False)
        loss = None
        for x, y in batches:
            state, loss = step(state, *shard_batch(mesh8, x, y))
        return float(loss)

    exact_loss = final_loss(get_strategy("ring"))
    int8_loss = final_loss(get_strategy("ring", compress="int8"))
    rel = abs(int8_loss - exact_loss) / abs(exact_loss)
    assert rel <= 0.01, (int8_loss, exact_loss, rel)
