"""Decoder-only transformer LM with pluggable dense / ring / ulysses /
flash attention.

A model family beyond the reference's capability surface (its only model is
a 32×32 CNN — ``part1/model.py``; SURVEY.md §2.3 records TP/SP/CP as
absent) added because long-context is first-class here: with
``attn_impl="ring"`` the module runs unchanged inside a ``shard_map`` whose
``seq_axis`` shards the sequence across devices, attention becomes the
exact blockwise ring of ``ops/ring_attention.py``, and context length
scales linearly with the number of chips.

TPU-first choices:
- pre-LN blocks, GELU MLP — all weight matmuls are large, static-shape
  einsums that tile straight onto the MXU;
- rotary position embeddings (RoPE): positions enter through a rotation of
  Q/K rather than a learned table, so a sequence-sharded device needs only
  its global position offset (``lax.axis_index``), not an embedding slice;
- bf16 trunk with fp32 logits/softmax (same policy as ``models/vgg.py``);
- zero data-dependent Python control flow — one traced XLA program.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from distributed_machine_learning_tpu.ops.ring_attention import (
    dense_self_attention,
    ring_self_attention,
)


def apply_rope(x: jax.Array, positions: jax.Array, base: float = 10000.0):
    """Rotate [B, L, H, D] by per-position angles; fp32 math, dtype
    preserved.  ``positions``: [L] (one stream position per slot) or
    [B, L] (per-ROW absolute positions — the batched-frontier decode
    path, where each batch row's committed stream has its own length)."""
    d_half = x.shape[-1] // 2
    freqs = base ** (-jnp.arange(d_half, dtype=jnp.float32) / d_half)
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [..., L, Dh/2]
    if positions.ndim == 1:
        cos = jnp.cos(angles)[None, :, None, :]
        sin = jnp.sin(angles)[None, :, None, :]
    else:  # [B, L] per-row positions
        cos = jnp.cos(angles)[:, :, None, :]
        sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rotated.astype(x.dtype)


def _repeat_kv(t: jax.Array, n_rep: int) -> jax.Array:
    """[B, L, Hkv, D] → [B, L, Hkv·n_rep, D]: expand grouped K/V heads so
    every attention impl sees full-width heads (XLA fuses the broadcast
    into the attention matmuls; only the decode *cache* stays narrow —
    that is GQA's memory win)."""
    if n_rep == 1:
        return t
    return jnp.repeat(t, n_rep, axis=2)


def _cached_mask(s, q_positions, S):
    """Causal frontier mask for the cached-attention einsums.  ``s``:
    [B, Hkv, rep, Lq, S] scores; ``q_positions``: [Lq] (one shared
    stream) or [B, Lq] (per-row frontiers — batched speculative
    decoding)."""
    if q_positions.ndim == 1:
        mask = jnp.arange(S)[None, :] <= q_positions[:, None]  # [Lq, S]
        return jnp.where(mask[None, None, None], s, -jnp.inf)
    mask = (
        jnp.arange(S)[None, None, :] <= q_positions[:, :, None]
    )  # [B, Lq, S]
    return jnp.where(mask[:, None, None], s, -jnp.inf)


def _cached_attention(q, k_cache, v_cache, q_positions):
    """Attention of fresh queries against the full K/V cache, GQA-native.

    ``q``: [B, Lq, H, D] at absolute positions ``q_positions`` ([Lq],
    or [B, Lq] for per-row frontiers — see :func:`_cached_mask`);
    ``k_cache``/``v_cache``: [B, Hkv, S, D] (Hkv | H) where slot j holds
    position j (zeros beyond the write frontier — masked out by
    causality, since unwritten slots all have j > max(q_positions)).
    The head-major cache layout keeps each head's slots contiguous in
    (slot, lane) tiles — the layout the flash-decode kernel DMAs at
    full bandwidth (head-minor [S, Hkv, D] tiles pad Hkv=4 sublanes to
    8, measured 8× slower DMA).  fp32 softmax, dtype preserved —
    matching :func:`dense_self_attention`.

    The query heads are RESHAPED into [Hkv, rep] groups and contracted
    against the narrow cache directly — no widened K/V is ever
    materialized.  Decode is bound by HBM reads of weights + cache, and
    a ``jnp.repeat`` of the cache every step would re-write (and
    re-read) rep× the cache bytes, forfeiting exactly the bandwidth GQA
    buys.
    """
    B, Lq, H, D = q.shape
    Hkv, S = k_cache.shape[1], k_cache.shape[2]
    rep = H // Hkv
    qg = q.astype(jnp.float32).reshape(B, Lq, Hkv, rep, D)
    s = jnp.einsum(
        "bqhrd,bhkd->bhrqk",
        qg,
        k_cache.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) * (1.0 / (D**0.5))
    s = _cached_mask(s, q_positions, S)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum(
        "bhrqk,bhkd->bqhrd", p, v_cache.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out.reshape(B, Lq, H, D).astype(q.dtype)


def _cached_attention_quant(q, k_int, ks, v_int, vs, q_positions):
    """:func:`_cached_attention` over an int8 cache WITHOUT materializing
    a dequantized f32 copy: the per-slot scales fold into the f32
    score/probability path — ``s·ks`` after the QK einsum, ``p·vs``
    before the PV einsum — algebraically identical to dequantize-then-
    attend, while the int8→f32 convert fuses into the einsums (HBM only
    ever reads the int8 bytes; a materialized f32 cache copy would cost
    4× the traffic the int8 cache exists to save)."""
    B, Lq, H, D = q.shape
    Hkv, S = k_int.shape[1], k_int.shape[2]
    rep = H // Hkv
    qg = q.astype(jnp.float32).reshape(B, Lq, Hkv, rep, D)
    s = jnp.einsum(
        "bqhrd,bhkd->bhrqk", qg, k_int.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) * (1.0 / (D**0.5))
    s = s * ks[:, :, None, None, :]  # fold the key scales, f32
    s = _cached_mask(s, q_positions, S)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum(
        "bhrqk,bhkd->bqhrd", p * vs[:, :, None, None, :],
        v_int.astype(jnp.float32), preferred_element_type=jnp.float32,
    )
    return out.reshape(B, Lq, H, D).astype(q.dtype)


# Two-tier int8-KV-cache dispatch (VERDICT r4 item 7; measured by
# bench/int8_tier.py): when True, single-token int8 decode picks at
# RUNTIME between the frontier-clamped Pallas kernel (early in the
# stream — it reads O(pos) while the einsum reads all S allocated
# slots) and the scale-folding einsum (late).  Measured r5 on-chip at
# S_alloc=32k (Hkv=8, D=64; 2000-iteration scanned slope):
#   - einsum: FLAT ~60 µs at every fill; kernel: 20 µs at pos/S=0.05
#     growing to 305 µs at 0.95 — crossover at pos/S ≈ 0.19 (r4's 0.36
#     estimate assumed the kernel 2.8× costlier per byte; it measures
#     ~5×, its exact-f32 dequant off the DMA roofline);
#   - compile cost of the tiered program (8L, 32k-token generate):
#     +4.6-5 s (11.3 s vs 6.7 s warm cache; 20.4 vs 15.7 cold).
# Verdict: default OFF — over any run-to-completion generation the
# mean fill is >= 0.5, so the sub-0.19 phase is ~1-2% end-to-end, not
# worth 5 s compile per serving shape.  Flip it ON for the workload the
# numbers DO favor: serving that allocates a generous max_new_tokens
# and usually stops early (fill stays below the crossover all request:
# up to ~39 µs/layer/step back, ~0.3 ms/step on an 8L model — the
# attention share drops ~3x).
_INT8_TIERED_DISPATCH = False
_INT8_TIER_BREAK_EVEN_PCT = 19  # measured crossover (bench/int8_tier.py)


def _flash_wins(L: int) -> bool:
    """attn_impl="auto" policy — delegates to the kernel module's shared
    ``flash_wins`` length rule (docs/PERF.md r02 crossover table)."""
    from distributed_machine_learning_tpu.ops.pallas.flash_attention import (
        flash_wins,
    )

    return flash_wins(L)


def _ring_flash_wins(chunk_len: int) -> bool:
    """ring → ring_flash upgrade policy (one source of truth for the CLI
    and programmatic callers): the per-chunk math is exactly the
    unsharded-flash regime applied to the LOCAL chunk, so the same
    measured length policy decides — delegate to ``flash_wins``, minus
    the lengths the single-chunk path handles by padding: the ring
    kernels operate on fixed chunk grids with no pad/slice wrapper, so
    a chunk Mosaic cannot tile natively stays on the einsum ring."""
    from distributed_machine_learning_tpu.ops.pallas.flash_attention import (
        _needs_pad,
        flash_wins,
    )

    return flash_wins(chunk_len) and not _needs_pad(chunk_len)


class Attention(nn.Module):
    """Multi-head causal self-attention.

    ``attn_impl``: "dense" (full XLA attention), "ring" (sequence sharded
    over ``seq_axis``, einsum chunk pairs — ``ops/ring_attention.py``),
    "ring_flash" (sequence sharded, flash-kernel chunk pairs —
    ``ops/pallas/ring_flash_attention.py``), "ulysses" (sequence sharded
    via all-to-all head re-sharding — ``ops/ulysses.py``), "flash" (the
    Pallas kernel — ``ops/pallas/flash_attention.py``), or "auto" (flash
    from the measured 512-context crossover up when the length tiles
    natively, always from 2048 up via the kernel's pad-and-slice path,
    dense below — see ``flash_wins``; for the sharded ring the analogous
    policy is ``_ring_flash_wins``).

    ``decode=True`` switches to KV-cached autoregressive inference: K/V
    land in a ``"cache"`` variable collection sized by the init-time
    input length, and each apply attends its (short) input against the
    whole cache — the O(1)-per-token decode path behind
    ``inference/generate.py``.
    """

    n_heads: int
    attn_impl: str = "dense"  # "dense" | "ring" | "ulysses" | "flash" | "auto"
    seq_axis: str = "seq"
    compute_dtype: Any = jnp.float32
    decode: bool = False
    # Grouped-query attention: K/V get n_kv_heads heads (< n_heads),
    # each shared by n_heads/n_kv_heads query heads; 1 = MQA.  None
    # keeps classic MHA with the fused qkv projection (and its param
    # layout — existing checkpoints are untouched).
    n_kv_heads: int | None = None
    # Decode KV-cache storage dtype (None = the K/V compute dtype).
    # Decode is bound by HBM reads of the cache, so a narrower cache
    # dtype is a direct bandwidth lever; attention math stays fp32
    # either way (_cached_attention upcasts).
    kv_cache_dtype: Any = None
    # When set (a jax.sharding.Mesh), the flash kernel runs inside a
    # fully-manual shard_map with the batch dim sharded over
    # ``flash_batch_axis`` (and, when ``flash_head_axis`` is set, the
    # head dim sharded over it — the Megatron TP layout; heads are
    # independent in flash and GQA groups stay aligned because
    # H_local = groups · Hkv_local on every shard).  This is how flash
    # composes with the GSPMD-partitioned steps (fsdp_pl / EP / TP),
    # whose jit could not otherwise partition the Mosaic custom call.
    # The activations must really be sharded that way (the shard_map
    # constrains them if the partitioner chose otherwise).
    flash_mesh: Any = None
    flash_batch_axis: str = "batch"
    flash_head_axis: str | None = None
    # None = manualize the WHOLE mesh (the GSPMD steps).  The 3-D step
    # calls from inside a region already manual over its pipe axis, so
    # it restricts the wrap to the remaining (batch, model) axes — the
    # union is still every axis, keeping the kernel fully local.
    flash_manual_axes: tuple | None = None
    # "int8" = weight-only quantized projections for serving decode
    # (ops/quant.py); None = full-precision nn.DenseGeneral.
    weight_quant: str | None = None
    # Manual Megatron tensor parallelism for DECODE (shard_map context,
    # parallel/tensor_parallel.py::make_tp_generate_fn): this module is
    # then configured at its LOCAL width (n_heads = H/tp), its
    # out-projection is row-parallel (each device holds the rows of its
    # heads), and the psum below completes the Megatron g-collective.
    # The out-proj bias must be pre-divided by tp (tp_decode_params) so
    # the psum reassembles it exactly.
    tp_axis: str | None = None
    # Explicit per-head width.  None = E // n_heads (the usual rule);
    # the manual-TP decode clone MUST set it to the GLOBAL head dim,
    # since its local n_heads no longer divides E into real head widths.
    head_dim: int | None = None
    # Multi-token decode calls attend the full cache instead of taking
    # the start-0 prefill fast path — speculative decoding's verify
    # pass (inference/speculative.py).  decode=True only.
    decode_continuation: bool = False

    @nn.compact
    def __call__(self, x, positions):
        B, L, E = x.shape
        if self.head_dim is None:
            assert E % self.n_heads == 0, "n_heads must divide d_model"
        head_dim = self.head_dim or E // self.n_heads

        def proj(features, axis, name):
            """nn.DenseGeneral, or its int8 twin when weight_quant is on
            (serving decode — ops/quant.py); same name → the quantized
            params from quantize_lm_params land in the same scope."""
            if self.weight_quant == "int8":
                from distributed_machine_learning_tpu.ops.quant import (
                    QuantDenseGeneral,
                )

                feats = features if isinstance(features, tuple) else (features,)
                return QuantDenseGeneral(
                    out_features=feats,
                    n_in_axes=len(axis) if isinstance(axis, tuple) else 1,
                    compute_dtype=self.compute_dtype,
                    name=name,
                )
            return nn.DenseGeneral(
                features=features, axis=axis, dtype=self.compute_dtype,
                name=name,
            )

        if self.n_kv_heads is None or self.n_kv_heads == self.n_heads:
            qkv = proj((3, self.n_heads, head_dim), -1, "qkv")(x)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B,L,H,Dh]
        else:
            if self.n_heads % self.n_kv_heads:
                raise ValueError(
                    f"n_kv_heads={self.n_kv_heads} must divide "
                    f"n_heads={self.n_heads}"
                )
            q = proj((self.n_heads, head_dim), -1, "q")(x)
            kv = proj((2, self.n_kv_heads, head_dim), -1, "kv")(x)
            k, v = kv[:, :, 0], kv[:, :, 1]  # [B, L, Hkv, Dh]
        q = apply_rope(q, positions)
        k = apply_rope(k, positions)
        n_rep = self.n_heads // k.shape[2]
        if self.decode:
            # Cache shape fixes the max sequence length at init time
            # (init runs with a [B, max_len] input — generate.py).  Keys
            # are RoPE-rotated at their absolute position before being
            # written, so cached entries never need re-rotation.
            cache_dtype = self.kv_cache_dtype or k.dtype
            quant_cache = jnp.dtype(cache_dtype) == jnp.int8
            # Head-major cache layout [B, Hkv, S, D]: each head's slots
            # form full (slot, lane) tiles, which is what lets the
            # flash-decode kernel (and the einsum) stream the cache at
            # HBM bandwidth — see _cached_attention's docstring.
            cshape = (k.shape[0], k.shape[2], k.shape[1], k.shape[3])
            ck = self.variable(
                "cache", "cached_key", jnp.zeros, cshape, cache_dtype
            )
            cv = self.variable(
                "cache", "cached_value", jnp.zeros, cshape, cache_dtype
            )
            if quant_cache:
                # int8 KV: one f32 scale per (kv head, slot) beside the
                # int8 rows — written together, folded into the f32
                # score/probability path by the scale-folding einsum
                # (_cached_attention_quant — the measured-fastest int8
                # dispatch at every context; see below).  Cache HBM
                # traffic halves vs bf16; scales are [Hkv, S] floats,
                # noise next to the [Hkv, S, D] rows.
                cks = self.variable(
                    "cache", "cached_key_scale", jnp.zeros, cshape[:3],
                    jnp.float32,
                )
                cvs = self.variable(
                    "cache", "cached_value_scale", jnp.zeros, cshape[:3],
                    jnp.float32,
                )
            if not self.is_initializing():
                # [L] positions: one shared frontier (start scalar).
                # [B, L]: per-ROW frontiers (batched speculative decode)
                # — each row writes its slots at its own offset, via a
                # vmapped slice-update (XLA lowers it to a scatter whose
                # windows are the tiny per-row [Hkv, L, D] fresh K/V —
                # decode-scale, not cache-scale, bytes).
                batched_frontier = positions.ndim == 2
                start = (
                    positions[:, 0] if batched_frontier else positions[0]
                )

                def _write(ref, t, sref=None):
                    t = t.swapaxes(1, 2)  # [B, Hkv, L, D]
                    if quant_cache:
                        amax = jnp.max(
                            jnp.abs(t.astype(jnp.float32)), axis=-1
                        )
                        s = jnp.where(amax > 0, amax / 127.0, 1.0)
                        t = jnp.clip(
                            jnp.round(t.astype(jnp.float32) / s[..., None]),
                            -127, 127,
                        ).astype(jnp.int8)
                        if batched_frontier:
                            sref.value = jax.vmap(
                                lambda c, u, s0: lax.dynamic_update_slice(
                                    c, u, (0, s0)
                                )
                            )(sref.value, s, start)
                        else:
                            sref.value = lax.dynamic_update_slice(
                                sref.value, s, (0, 0, start)
                            )
                    t = t.astype(ref.value.dtype)
                    if batched_frontier:
                        ref.value = jax.vmap(
                            lambda c, u, s0: lax.dynamic_update_slice(
                                c, u, (0, s0, 0)
                            )
                        )(ref.value, t, start)
                    else:
                        ref.value = lax.dynamic_update_slice(
                            ref.value, t, (0, 0, start, 0)
                        )

                _write(ck, k, cks if quant_cache else None)
                _write(cv, v, cvs if quant_cache else None)
                if L > 1 and self.decode_continuation:
                    # Mid-stream multi-token continuation (speculative
                    # decoding's verify pass): the fresh queries attend
                    # the FULL cache — prefix plus the just-written
                    # fresh K/V — causally masked by absolute position.
                    # _cached_attention handles Lq > 1 natively; at the
                    # verify shape (Lq = γ+1, small) the f32 score
                    # tensor is tiny, so no kernel dispatch is needed.
                    if quant_cache:
                        out = _cached_attention_quant(
                            q, ck.value, cks.value, cv.value, cvs.value,
                            positions,
                        )
                    else:
                        out = _cached_attention(
                            q, ck.value, cv.value, positions
                        )
                elif L > 1:
                    # PREFILL (the one multi-token call, at start == 0 —
                    # generate.py's contract; in batched-frontier mode
                    # every row prefills from 0, so row 0's positions
                    # speak for all): the cache was empty, so attention
                    # over the prompt is plain causal self-attention over
                    # the fresh K/V.  Routing it through the training
                    # kernels instead of _cached_attention avoids
                    # materializing the f32 [B, H, L, S] score tensor
                    # against the whole cache (34 GB at an 8k prompt) —
                    # flash when the length qualifies, dense below.
                    if _flash_wins(L):
                        from distributed_machine_learning_tpu.ops.pallas.flash_attention import (  # noqa: E501
                            flash_self_attention,
                        )

                        out = flash_self_attention(q, k, v)
                    else:
                        out = dense_self_attention(
                            q, _repeat_kv(k, n_rep), _repeat_kv(v, n_rep),
                            positions[0] if batched_frontier else positions,
                        )
                else:
                    # Narrow cache straight into GQA-native cached
                    # attention — no repeat, no widened materialization.
                    # Dispatch (all measured on-chip, docs/PERF.md):
                    # - int8 caches: ALWAYS the scale-folding einsum
                    #   (_cached_attention_quant) — XLA fuses the s8
                    #   convert into the dot, so HBM reads int8 bytes,
                    #   and it beats the kernel at any filled cache
                    #   (the kernel's exact-f32 dequant takes it off
                    #   its DMA-bound point).  Numbers: r5's scanned-
                    #   slope bench (bench/int8_tier.py — the r4
                    #   figures of 29/103/217 µs vs 83/282/612 came
                    #   from chained dispatches, which that bench
                    #   showed carry tunnel-RTT jitter into µs ops;
                    #   direction right, absolutes superseded)
                    #   measures the einsum flat ~60 µs at 32k alloc
                    #   vs the kernel's O(pos) 20→305 µs ladder —
                    #   einsum from pos/S ≈ 0.19 of the ALLOCATION up.
                    #   Caveat, priced in AND measured (r5,
                    #   bench/int8_tier.py): the einsum reads all S
                    #   ALLOCATED slots while the kernel's frontier
                    #   clamp reads O(pos) — measured crossover at
                    #   pos/S ≈ 0.19 (einsum flat ~60 µs at 32k alloc;
                    #   kernel 20→305 µs across the fill ladder), and
                    #   the mean of pos/S over ANY full generation is
                    #   (Lp/S + 1)/2 ≥ 0.5, so the einsum wins
                    #   integrated over every run-to-completion shape.
                    #   The tiered lax.cond alternative costs a
                    #   measured +4.6-5 s compile per serving shape
                    #   for a ~1-2% end-to-end win — kept available as
                    #   _INT8_TIERED_DISPATCH (above) for the one
                    #   workload that inverts the math: generous
                    #   max_new allocations that usually stop early;
                    # - long bf16/f32 caches (≥4k): the flash-decode
                    #   kernel (frontier-clamped O(pos) reads);
                    # - short bf16/f32 caches: the head-major einsum
                    #   (the kernel's per-grid-step overhead loses to
                    #   XLA's single fused op — 84 vs 48 µs at S=2k).
                    from distributed_machine_learning_tpu.ops.pallas.decode_attention import (  # noqa: E501
                        cached_flash_attention,
                        decode_flash_qualifies,
                    )

                    S_alloc = ck.value.shape[2]
                    if quant_cache:
                        if (
                            _INT8_TIERED_DISPATCH
                            and not batched_frontier
                            and decode_flash_qualifies(S_alloc)
                        ):
                            # Runtime two-tier switch: kernel while the
                            # cache is mostly empty, einsum once filled
                            # past the break-even.  Gated off by default
                            # (see _INT8_TIERED_DISPATCH).
                            out = lax.cond(
                                positions[0] * 100
                                < S_alloc * _INT8_TIER_BREAK_EVEN_PCT,
                                lambda q, ki, ks, vi, vs, p:
                                    cached_flash_attention(
                                        q, ki, vi, p[0],
                                        k_scale=ks, v_scale=vs,
                                    ),
                                _cached_attention_quant,
                                q, ck.value, cks.value, cv.value,
                                cvs.value, positions,
                            )
                        else:
                            out = _cached_attention_quant(
                                q, ck.value, cks.value, cv.value,
                                cvs.value, positions,
                            )
                    elif (
                        not batched_frontier
                        and decode_flash_qualifies(S_alloc)
                        and S_alloc >= 4096
                    ):
                        # The flash-decode kernel clamps its DMA at ONE
                        # scalar frontier; per-row frontiers (batched
                        # speculative decode) take the einsum, whose
                        # mask is per-row for free.
                        out = cached_flash_attention(
                            q, ck.value, cv.value, positions[0]
                        )
                    else:
                        out = _cached_attention(
                            q, ck.value, cv.value, positions
                        )
            else:
                # Init-time shape pass (is_initializing): positions may
                # be per-row [B, L] under the batched frontier — row 0
                # speaks for the shapes.
                out = dense_self_attention(
                    q, _repeat_kv(k, n_rep), _repeat_kv(v, n_rep),
                    positions[0] if positions.ndim == 2 else positions,
                )
        elif self.attn_impl == "ring":
            # GQA rotates the NARROW K/V chunks (ICI bytes ÷ the group
            # factor — ring_self_attention widens locally per block).
            out = ring_self_attention(
                q, k, v, self.seq_axis, lax.axis_size(self.seq_axis)
            )
        elif self.attn_impl == "ring_flash":
            from distributed_machine_learning_tpu.ops.pallas.ring_flash_attention import (
                ring_flash_self_attention,
            )

            # GQA rotates the NARROW K/V chunks around the ring (ICI and
            # traveling-gradient traffic shrink by the group factor).
            out = ring_flash_self_attention(
                q, k, v, self.seq_axis, lax.axis_size(self.seq_axis)
            )
        elif self.attn_impl == "ulysses":
            from distributed_machine_learning_tpu.ops.ulysses import (
                ulysses_self_attention,
            )

            # GQA stays NARROW into the all-to-all: when the sequence-
            # axis size divides the KV heads, ulysses packs q (viewed
            # [.., Hkv, rep, D]) with the narrow k/v into ONE collective
            # split on the shared Hkv axis — block alignment by
            # construction, ICI bytes ÷ the group factor; widening
            # happens after the re-shard, or not at all on the flash
            # path (the kernel is GQA-native).
            out = ulysses_self_attention(
                q, k, v, self.seq_axis, lax.axis_size(self.seq_axis)
            )
        elif self.attn_impl == "flash" or (
            self.attn_impl == "auto" and _flash_wins(L)
        ):
            from distributed_machine_learning_tpu.ops.pallas.flash_attention import (
                flash_self_attention,
            )

            # GQA stays narrow: the kernel's K/V index maps divide by the
            # group factor, so no repeated K/V ever hits HBM.
            if self.flash_mesh is not None:
                # Inside a GSPMD-partitioned step (fsdp_pl / EP / TP)
                # the Mosaic custom call has no sharding rules — so run
                # it under a FULLY-manual shard_map over the whole mesh:
                # the kernel then sees LOCAL per-device shapes and never
                # meets the partitioner on ANY axis.  The batch dim
                # shards over flash_batch_axis and (under TP) the head
                # dim over flash_head_axis; activations are replicated
                # over every remaining mesh axis (e.g. EP's expert
                # axis), which the unmentioned-axis convention expresses
                # as-is.  (Manual over a subset of axes would leave the
                # custom call under automatic propagation for the rest —
                # the hazard this wrap exists to remove.)
                from jax.sharding import PartitionSpec as _P

                from distributed_machine_learning_tpu.runtime.mesh import (
                    shard_map_no_check,
                )

                spec = _P(self.flash_batch_axis, None,
                          self.flash_head_axis, None)
                # Nested inside another shard_map (the 3-D step's
                # pipe-manual region), jax requires the CONTEXT abstract
                # mesh — whose axis types record what is already manual
                # — rather than the all-Auto concrete mesh.
                ctx_mesh = jax.sharding.get_abstract_mesh()
                wrap_mesh = (ctx_mesh if getattr(ctx_mesh, "axis_names", ())
                             else self.flash_mesh)
                out = shard_map_no_check(
                    flash_self_attention,
                    mesh=wrap_mesh,
                    in_specs=(spec, spec, spec),
                    out_specs=spec,
                    manual_axes=self.flash_manual_axes,
                )(q, k, v)
            else:
                out = flash_self_attention(q, k, v)
        else:
            out = dense_self_attention(
                q, _repeat_kv(k, n_rep), _repeat_kv(v, n_rep), positions
            )
        y = proj(E, (-2, -1), "out")(out)
        if self.tp_axis is not None:
            y = lax.psum(y, self.tp_axis)
        return y


def _mlp_sublayer(mdl: "Block", h: jax.Array) -> jax.Array:
    """LN2 + feed-forward sub-layer of a Block (residual added by the
    caller).  A module-level function (first arg = the Block) so it can be
    lifted through ``nn.remat`` for the selective-remat policy without
    changing the parameter tree: the same ``ln2``/``fc_in``/``fc_out``
    names land in the same scope whether or not the wrap is applied, so
    checkpoints are layout-compatible across remat policies."""
    d_out = h.shape[-1]
    h = nn.LayerNorm(dtype=mdl.compute_dtype, name="ln2")(h)
    if mdl.mlp_factory is not None:
        return mdl.mlp_factory()(h)
    if mdl.weight_quant == "int8":
        from distributed_machine_learning_tpu.ops.quant import (
            QuantDenseGeneral,
        )

        h = QuantDenseGeneral(
            out_features=(mdl.d_ff,), compute_dtype=mdl.compute_dtype,
            name="fc_in",
        )(h)
        h = nn.gelu(h)
        h = QuantDenseGeneral(
            out_features=(d_out,),
            compute_dtype=mdl.compute_dtype, name="fc_out",
        )(h)
    else:
        h = nn.Dense(mdl.d_ff, dtype=mdl.compute_dtype, name="fc_in")(h)
        h = nn.gelu(h)
        h = nn.Dense(d_out, dtype=mdl.compute_dtype, name="fc_out")(h)
    if mdl.tp_axis is not None:
        # Manual TP decode: fc_in is column-parallel (local d_ff slice),
        # fc_out row-parallel — this psum is Megatron's second
        # g-collective (fc_out's bias pre-divided by tp, as for the
        # attention out-projection).
        h = jax.lax.psum(h, mdl.tp_axis)
    return h


class Block(nn.Module):
    """Pre-LN transformer block.  ``mlp_factory`` swaps the feed-forward
    sub-layer (e.g. for a routed MoE MLP — ``models/moe.py``) while the
    residual/LN/attention wiring stays in one place.

    ``remat_mlp=True`` is the SELECTIVE remat policy: only the LN2+MLP
    sub-layer is checkpointed; the attention path's residuals — including
    the flash kernel's saved ``(out, lse)`` (O(L·D), cheap) — stay
    resident, so the backward pass never re-runs the O(L²) attention
    forward.  Whole-block remat re-runs everything (flash forward
    included) in backward — the ~4/3 HFU overhead docs/PERF.md's 16k/32k
    rows paid in round 3; this policy converts most of that recompute
    back into real tokens/sec at the cost of ~6·L·E saved activation
    bytes per layer instead of ~1·L·E."""

    n_heads: int
    d_ff: int
    attn_impl: str
    seq_axis: str
    compute_dtype: Any
    mlp_factory: Any = None  # () -> nn.Module, or None for the dense MLP
    decode: bool = False
    n_kv_heads: int | None = None
    kv_cache_dtype: Any = None
    flash_mesh: Any = None
    flash_batch_axis: str = "batch"
    flash_head_axis: str | None = None
    flash_manual_axes: tuple | None = None
    weight_quant: str | None = None
    remat_mlp: bool = False
    tp_axis: str | None = None  # manual TP decode (see Attention.tp_axis)
    head_dim: int | None = None  # explicit head width (TP decode clones)
    decode_continuation: bool = False  # verify-pass decode (speculative)

    @nn.compact
    def __call__(self, x, positions):
        h = nn.LayerNorm(dtype=self.compute_dtype, name="ln1")(x)
        x = x + Attention(
            n_heads=self.n_heads,
            attn_impl=self.attn_impl,
            seq_axis=self.seq_axis,
            compute_dtype=self.compute_dtype,
            decode=self.decode,
            n_kv_heads=self.n_kv_heads,
            kv_cache_dtype=self.kv_cache_dtype,
            flash_mesh=self.flash_mesh,
            flash_batch_axis=self.flash_batch_axis,
            flash_head_axis=self.flash_head_axis,
            flash_manual_axes=self.flash_manual_axes,
            weight_quant=self.weight_quant,
            tp_axis=self.tp_axis,
            head_dim=self.head_dim,
            decode_continuation=self.decode_continuation,
            name="attn",
        )(h, positions)
        if self.remat_mlp and not self.decode:
            return x + nn.remat(_mlp_sublayer)(self, x)
        return x + _mlp_sublayer(self, x)


class TransformerLM(nn.Module):
    """Causal LM: tokens [B, L(local)] → logits [B, L(local), vocab].

    With ``attn_impl="ring"`` or ``"ulysses"`` (the two sequence-sharded
    context-parallel schemes — ppermute K/V rotation vs all-to-all head
    re-sharding) the module must run inside ``shard_map`` with ``seq_axis``
    bound; it derives its global position offset from ``lax.axis_index`` so
    sequence-sharded and unsharded runs produce identical logits.
    """

    vocab_size: int
    d_model: int = 256
    n_layers: int = 4
    n_heads: int = 8
    d_ff: int | None = None
    attn_impl: str = "dense"
    seq_axis: str = "seq"
    compute_dtype: Any = jnp.float32
    decode: bool = False
    # GQA: n_kv_heads < n_heads shares each K/V head across a group of
    # query heads (1 = MQA) — the decode KV cache shrinks by the group
    # factor.  None = classic MHA (fused qkv param layout).
    n_kv_heads: int | None = None
    # Decode KV-cache storage dtype (None = compute dtype); see
    # ``Attention.kv_cache_dtype``.
    kv_cache_dtype: Any = None
    # Flash-under-GSPMD composition; see ``Attention.flash_mesh``.
    flash_mesh: Any = None
    flash_batch_axis: str = "batch"
    flash_head_axis: str | None = None
    flash_manual_axes: tuple | None = None
    # "int8" = weight-only quantized serving (decode mode only): every
    # kernel-bearing projection reads int8 weights through the Pallas
    # kernel (ops/quant.py; params from quantize_lm_params).  Embeddings
    # stay full precision (a gather).
    weight_quant: str | None = None
    # Manual Megatron TP for DECODE: set by make_tp_generate_fn's
    # shard_map wrap, with the model configured at LOCAL width
    # (n_heads=H/tp, n_kv_heads=Hkv/tp, d_ff=F/tp, head_dim pinned to
    # the global per-head width).  Embed + lm_head + LayerNorms stay
    # replicated (the embed gather reads only B rows per step; sharding
    # lm_head would shard the logits).  Decode-only.
    tp_axis: str | None = None
    head_dim: int | None = None
    # Multi-token decode applies attend the full cache (speculative
    # decoding's verify pass — inference/speculative.py) instead of
    # assuming the start-0 prefill contract.
    decode_continuation: bool = False
    # Per-ROW cache frontiers for decode: the ``idx`` cache variable is
    # [B] instead of a scalar, positions are [B, L], and each row's K/V
    # land at its own offset.  Batched speculative decoding needs this
    # (acceptance length is data-dependent PER ROW); plain generate
    # keeps the scalar (every row's stream has one length).  Prefill
    # must still start every row at 0.
    decode_batched_frontier: bool = False
    remat: bool = False  # jax.checkpoint each block: activation memory
    # drops from O(L·E) per layer to per-block boundaries, recomputing the
    # block in backward — the HBM-for-FLOPs trade that lets long-context
    # (ring/ulysses) runs fit; FLOPs +~33%, memory ÷ ~n_layers.
    # Which remat policy `remat=True` applies:
    #   "mlp" (default)  — selective: checkpoint only the LN2+MLP
    #     sub-layer; attention residuals (incl. the flash kernel's
    #     out+lse) stay saved, so backward never re-runs the O(L²)
    #     attention forward.  ~6·L·E saved bytes/layer.
    #   "block" — whole-block jax.checkpoint (the maximal-savings
    #     fallback, ~1·L·E bytes/layer): use when "mlp" does not fit.
    remat_policy: str = "mlp"

    @nn.compact
    def __call__(self, tokens, *, train: bool = False,
                 return_hidden: bool = False):
        """``return_hidden=True`` returns the post-``ln_f`` hidden states
        [B, L, E] instead of logits, skipping the ``lm_head`` projection —
        the entry point for the fused head+loss (``ops/fused_ce.py``),
        which never materializes [B, L, vocab]."""
        del train  # no dropout/BN — kept for the shared train-step interface
        B, L = tokens.shape
        if self.weight_quant is not None and not self.decode:
            raise ValueError(
                "weight_quant is a serving-decode feature (int8 weights "
                "are not trainable); clone with decode=True — "
                "inference/generate.py does this"
            )
        if self.tp_axis is not None and not self.decode:
            raise ValueError(
                "tp_axis is the manual TP-decode wiring "
                "(make_tp_generate_fn); training-time TP is the GSPMD "
                "step (parallel/tensor_parallel.py)"
            )
        if self.decode:
            if self.attn_impl != "dense":
                raise ValueError(
                    "decode mode runs dense cached attention; clone the "
                    'model with attn_impl="dense" (generate.py does this)'
                )
            # Autoregressive position tracking: one counter for the whole
            # stack (every layer sees the same absolute positions) — or
            # one PER ROW under decode_batched_frontier (batched
            # speculative decoding: rows commit different lengths).
            if self.decode_batched_frontier:
                idx = self.variable(
                    "cache", "idx", lambda: jnp.zeros((B,), jnp.int32)
                )
                start = idx.value  # [B]
                positions = start[:, None] + jnp.arange(L)[None, :]
            else:
                idx = self.variable(
                    "cache", "idx", lambda: jnp.zeros((), jnp.int32)
                )
                start = idx.value
                positions = start + jnp.arange(L)
            if not self.is_initializing():
                idx.value = start + L
        else:
            if self.attn_impl in ("ring", "ring_flash", "ulysses"):
                offset = lax.axis_index(self.seq_axis) * L
            else:
                offset = 0
            positions = offset + jnp.arange(L)
        x = nn.Embed(
            self.vocab_size, self.d_model, dtype=self.compute_dtype, name="embed"
        )(tokens)
        d_ff = self.d_ff or 4 * self.d_model
        # nn.remat must see concrete (non-decode) blocks: the decode path
        # mutates cache variables, which checkpointing cannot replay.
        if self.remat_policy not in ("mlp", "block"):
            raise ValueError(
                f"remat_policy must be 'mlp' or 'block', got "
                f"{self.remat_policy!r}"
            )
        rematting = self.remat and not self.decode
        whole_block = rematting and self.remat_policy == "block"
        block_cls = nn.remat(Block) if whole_block else Block
        remat_mlp = rematting and self.remat_policy == "mlp"
        for i in range(self.n_layers):
            x = block_cls(
                n_heads=self.n_heads,
                d_ff=d_ff,
                attn_impl=self.attn_impl,
                seq_axis=self.seq_axis,
                compute_dtype=self.compute_dtype,
                decode=self.decode,
                n_kv_heads=self.n_kv_heads,
                kv_cache_dtype=self.kv_cache_dtype,
                flash_mesh=self.flash_mesh,
                flash_batch_axis=self.flash_batch_axis,
                flash_head_axis=self.flash_head_axis,
                flash_manual_axes=self.flash_manual_axes,
                weight_quant=self.weight_quant,
                remat_mlp=remat_mlp,
                tp_axis=self.tp_axis,
                head_dim=self.head_dim,
                decode_continuation=self.decode_continuation,
                name=f"block_{i}",
            )(x, positions)
        x = nn.LayerNorm(dtype=self.compute_dtype, name="ln_f")(x)
        if return_hidden:
            return x
        if self.weight_quant == "int8":
            from distributed_machine_learning_tpu.ops.quant import (
                QuantDenseGeneral,
            )

            logits = QuantDenseGeneral(
                out_features=(self.vocab_size,),
                compute_dtype=self.compute_dtype, name="lm_head",
            )(x)
        else:
            logits = nn.Dense(
                self.vocab_size, dtype=self.compute_dtype, name="lm_head"
            )(x)
        return logits.astype(jnp.float32)
