from distributed_machine_learning_tpu.ops.collectives import (
    all_reduce_sum,
    all_reduce_mean,
    gather_scatter_sum,
)
from distributed_machine_learning_tpu.ops.ring import (
    WireScheme,
    get_wire_scheme,
    ring_all_reduce,
    ring_all_reduce_flat,
    ring_wire_bytes,
)

__all__ = [
    "all_reduce_sum",
    "all_reduce_mean",
    "gather_scatter_sum",
    "WireScheme",
    "get_wire_scheme",
    "ring_all_reduce",
    "ring_all_reduce_flat",
    "ring_wire_bytes",
]
