"""Flash-decode: single-token cached attention as a Pallas TPU kernel,
with optional int8-quantized KV cache.

The decode step's attention is a matvec against the whole KV cache —
pure HBM bandwidth — and the XLA einsum path (`_cached_attention`)
reads every one of the S *allocated* slots every step, zeros beyond the
write frontier included; an int8 cache would additionally dequantize
through HBM the way int8 weights do (see quant_matmul.py).  This kernel
fixes both:

- **Frontier clamping**: the K/V block index map clamps to the last
  block containing the current position (a scalar-prefetch value), so
  Pallas elides the DMA for every block past the frontier — reads are
  O(position), not O(allocated cache).  Early in a long-max-tokens
  generation that is nearly the whole cache.
- **In-register int8**: with ``kv_cache_dtype="int8"`` the cache stores
  int8 rows + one f32 scale per (kv head, slot); blocks dequantize in
  VMEM registers after the DMA — HBM traffic halves vs bf16 (quarters
  vs f32), which is the decode speed *and* the 2× longer-context
  memory headroom.

Layout is load-bearing: the cache is **head-major** [B, Hkv, S, D]
(written that way by ``models/transformer.py``), so a K/V block's last
two dims are a full (block_s, D) tile.  The first cut of this kernel
used the activation-order [B, S, Hkv, D] cache, whose (Hkv=4, D) tile
tail pads every slot's 4 sublanes to 8 — measured ~60 GB/s effective
DMA (8× off), with a 4× recovery just from raising Hkv to 16.  Same
grid, same math, head-major tiles: full bandwidth.

Grid ``(B, S/block_s)`` with the S axis innermost (sequential — it
carries the online-softmax scratch); a static Python loop over the ≤16
KV heads runs each per-group [rep, block_s] score tile through the same
``_online_update`` recurrence as the training kernels — one source of
truth for the softmax arithmetic (base-2, f32 state).  The per-head
matmuls are narrow (rep ≤ 16 rows), which costs little here: the
kernel is DMA-bound by construction.  Masking needs only the frontier
block (slots are written in order, so every block below it is fully
valid).  No backward pass: decode is inference.


NOTE (round 4): the kernel's int8-dequant mode is SUPERSEDED in
production by the scale-folding einsum
(models/transformer.py::_cached_attention_quant) — XLA fuses the
s8 convert into the attention dots and measures ~2.7-2.9x faster
at every context (docs/PERF.md), so the model dispatch never
routes int8 caches here anymore.  The mode stays implemented and
tested as the Pallas reference for in-register dequant; the
kernel's production role is long bf16/f32 caches (>= 4k), where
its frontier-clamped O(pos) DMA wins.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from distributed_machine_learning_tpu.ops.pallas.flash_attention import (
    _LANES,
    LOG2E,
    NEG_INF,
    _interpret,
    _online_update,
)

try:
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False


def pick_block_s(S: int, target: int = 512) -> int | None:
    """Largest divisor of S that is <= target and a multiple of 128 (or
    S itself when S <= 128): block_s is the lane dim of the f32 scale
    blocks and the sublane dim of the K/V tiles, so 128 keeps every
    block at native tiling.  ``generate.py`` rounds its cache
    allocation to a 512 multiple so serving always tiles."""
    if S <= 128:
        return S
    best = None
    for b in range(128, min(S, target) + 1, 128):
        if S % b == 0:
            best = b
    return best


def decode_flash_qualifies(S: int, min_block: int = 128) -> bool:
    """Dispatch rule for the decode kernel vs the einsum fallback: the
    cache length must tile into full S blocks (tiny test caches and
    awkward lengths stay on the einsum)."""
    b = pick_block_s(S)
    return b is not None and (b >= min_block or b == S)


def _decode_kernel(
    pos_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref,
    *, block_s: int, n_rep: int, scale: float, quant: bool,
):
    si = pl.program_id(1)
    pos = pos_ref[0]
    frontier = pos // block_s

    @pl.when(si == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _update(masked: bool):
        n_kv = k_ref.shape[1]
        D = k_ref.shape[3]
        H = n_kv * n_rep
        width = n_kv * block_s
        # ONE dot over the flattened [Hkv·bS, D] block computes every
        # (query head, kv head) score pair; off-group pairs — cross
        # terms GQA never attends — are pushed to NEG_INF, so their
        # probabilities are exactly 0 and the single p·V dot below sums
        # only each row's own group.  This replaces a per-head loop of
        # [rep, D] matmuls (rep ≤ 16 rows: all MXU issue latency, ~2 µs
        # of overhead per grid step measured) with two full-width MXU
        # streams; the Hkv× extra MACs are free under the DMA.
        if quant:
            # Dequantize in 3D first (a lane-dim broadcast of the
            # [Hkv, bS] scales — Mosaic cannot shape-cast the scales
            # themselves into the flattened [width] vector), THEN merge
            # the leading dims, which is the same layout-contiguous
            # reshape the bf16 path uses.  The multiply stays in f32
            # (int8 values are exact in f32; so are the scales), so the
            # kernel adds NO rounding beyond the int8 storage itself and
            # matches the f32 einsum fallback's arithmetic — the bf16
            # dequant it replaces cost up to ~0.4% extra relative error.
            # The f32 matmuls this implies are free here: the kernel is
            # DMA-bound by construction (module docstring).
            k3 = k_ref[0].astype(jnp.float32) * ks_ref[0][:, :, None]
            v3 = v_ref[0].astype(jnp.float32) * vs_ref[0][:, :, None]
            k_all = k3.reshape(width, D)
            v_all = v3.reshape(width, D)
        else:
            k_all = k_ref[0].reshape(width, D)  # layout-contiguous
            v_all = v_ref[0].reshape(width, D)
        q_all = q_ref[0, 0]  # [H, D]
        s = jax.lax.dot_general(
            q_all.astype(k_all.dtype), k_all, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * (scale * LOG2E)
        col_group = (
            jax.lax.broadcasted_iota(jnp.int32, (H, width), 1) // block_s
        )
        row_group = (
            jax.lax.broadcasted_iota(jnp.int32, (H, width), 0) // n_rep
        )
        valid = col_group == row_group
        if masked:
            slot = si * block_s + (
                jax.lax.broadcasted_iota(jnp.int32, (H, width), 1) % block_s
            )
            valid = valid & (slot <= pos)
        s = jnp.where(valid, s, NEG_INF)
        # causal=True: _online_update zeroes the NEG_INF entries' p.
        m_new, l_new, acc_new = _online_update(
            s, m_ref[:, 0], l_ref[:, 0], acc_ref[:, :], v_all, causal=True
        )
        acc_ref[:, :] = acc_new
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(si < frontier)
    def _interior():
        _update(False)

    @pl.when(si == frontier)
    def _boundary():
        _update(True)

    @pl.when(si == pl.num_programs(1) - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0, 0] = (acc_ref[:, :] / l[:, None]).astype(o_ref.dtype)


def cached_flash_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    pos: jax.Array,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """One decode step of attention against the head-major cache.

    ``q``: [B, 1, H, D] at absolute position ``pos`` (scalar int32);
    ``k_cache``/``v_cache``: [B, Hkv, S, D] with slot j holding position
    j, zeros beyond the frontier.  With int8 caches, ``k_scale``/
    ``v_scale`` are the [B, Hkv, S] f32 per-(head, slot) scales.
    Returns [B, 1, H, D] in ``q.dtype`` — same contract (fp32 softmax,
    GQA-native narrow cache) as ``_cached_attention``.
    """
    B, Lq, H, D = q.shape
    if Lq != 1:
        raise ValueError(f"decode kernel is single-token (got Lq={Lq})")
    Hkv, S = k_cache.shape[1], k_cache.shape[2]
    n_rep = H // Hkv
    quant = k_cache.dtype == jnp.int8
    if quant and (k_scale is None or v_scale is None):
        raise ValueError("int8 caches need k_scale/v_scale")
    # int8 favors big streamed blocks: the in-register dequant is VPU
    # work proportional to bytes, so fewer grid steps amortize the
    # per-step fixed cost the dequant adds (measured at 32k: bS 2048 →
    # 291 µs vs 384 µs at 512).  bf16 measured best at 512.
    block_s = pick_block_s(S, target=2048 if quant else 512)
    if block_s is None:
        raise ValueError(
            f"cache length {S} does not tile; check decode_flash_qualifies"
        )
    if not _HAS_PLTPU:  # pragma: no cover
        raise RuntimeError("pallas TPU support unavailable")
    if not quant:
        # Dummy scale operands keep ONE kernel signature; block index 0
        # never moves, so only 128 lanes per head are ever DMA'd.
        k_scale = jnp.ones((B, Hkv, 128), jnp.float32)
        v_scale = k_scale
    pos_arr = jnp.asarray(pos, jnp.int32).reshape((1,))
    n_blocks = S // block_s

    kv_spec = pl.BlockSpec(
        (1, Hkv, block_s, D),
        lambda b, s, p: (b, 0, jnp.minimum(s, p[0] // block_s), 0),
    )
    scale_spec = pl.BlockSpec(
        (1, Hkv, block_s if quant else 128),
        (lambda b, s, p: (b, 0, jnp.minimum(s, p[0] // block_s)))
        if quant
        else (lambda b, s, p: (b, 0, 0)),
    )
    q_spec = pl.BlockSpec((1, 1, H, D), lambda b, s, p: (b, 0, 0, 0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, n_blocks),
        in_specs=[q_spec, kv_spec, kv_spec, scale_spec, scale_spec],
        out_specs=q_spec,
        scratch_shapes=[
            pltpu.VMEM((H, _LANES), jnp.float32),  # running max (log2)
            pltpu.VMEM((H, _LANES), jnp.float32),  # running normalizer
            pltpu.VMEM((H, D), jnp.float32),  # output accumulator
        ],
    )
    kernel = functools.partial(
        _decode_kernel,
        block_s=block_s,
        n_rep=n_rep,
        scale=1.0 / (D**0.5),
        quant=quant,
    )
    compiler_params = (
        {}
        if _interpret()
        else {
            "compiler_params": pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")
            )
        }
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, 1, H, D), q.dtype),
        interpret=_interpret(),
        **compiler_params,
    )(pos_arr, q, k_cache, v_cache, k_scale, v_scale)


# ---------------------------------------------------------------------------
# Paged (block-table) decode attention — ISSUE 19
# ---------------------------------------------------------------------------
# The continuous-batching engine (inference/continuous.py) keeps KV
# residency in a SHARED physical pool of fixed-size blocks
# ([num_blocks, Hkv, block_s, D], ops on it managed by
# inference/kv_blocks.py) instead of a per-sequence [B, S, D] slab;
# each in-flight lane w owns a block table mapping its logical block j
# to a physical pool block.  The ragged entry point below is the
# decode dispatch for that layout: one grid where every lane reads its
# OWN frontier-clamped walk of the pool through the scalar-prefetched
# table — the vLLM PagedAttention access pattern on the flash-decode
# kernel above.  Per-lane reads stay O(position); lanes at different
# lengths share one dispatch, which is what makes iteration-level
# batching a single program instead of a per-length group loop.


def _paged_kernel(
    tbl_ref, pos_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
    *, block_s: int, n_rep: int, scale: float,
):
    si = pl.program_id(1)
    pos = pos_ref[pl.program_id(0)]
    frontier = pos // block_s

    @pl.when(si == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _update(masked: bool):
        n_kv = k_ref.shape[0]
        D = k_ref.shape[2]
        H = n_kv * n_rep
        width = n_kv * block_s
        k_all = k_ref[:].reshape(width, D)
        v_all = v_ref[:].reshape(width, D)
        q_all = q_ref[0, 0]  # [H, D]
        s = jax.lax.dot_general(
            q_all.astype(k_all.dtype), k_all, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * (scale * LOG2E)
        col_group = (
            jax.lax.broadcasted_iota(jnp.int32, (H, width), 1) // block_s
        )
        row_group = (
            jax.lax.broadcasted_iota(jnp.int32, (H, width), 0) // n_rep
        )
        valid = col_group == row_group
        if masked:
            slot = si * block_s + (
                jax.lax.broadcasted_iota(jnp.int32, (H, width), 1) % block_s
            )
            valid = valid & (slot <= pos)
        s = jnp.where(valid, s, NEG_INF)
        m_new, l_new, acc_new = _online_update(
            s, m_ref[:, 0], l_ref[:, 0], acc_ref[:, :], v_all, causal=True
        )
        acc_ref[:, :] = acc_new
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(si < frontier)
    def _interior():
        _update(False)

    @pl.when(si == frontier)
    def _boundary():
        _update(True)

    @pl.when(si == pl.num_programs(1) - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0, 0] = (acc_ref[:, :] / l[:, None]).astype(o_ref.dtype)


def paged_attention_reference(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_tables: jax.Array,
    positions: jax.Array,
) -> jax.Array:
    """XLA reference for :func:`paged_flash_attention` — the gather
    formulation (pool rows indexed by the block table, then the same
    masked fp32-softmax attention as ``_cached_attention``).  This is
    also the CPU serving path: on hosts without a Pallas TPU backend
    the engine dispatches here, and the kernel's interpret-mode parity
    test pins the two together.

    ``q``: [W, 1, H, D] — one in-flight decode token per lane;
    ``k_pool``/``v_pool``: [num_blocks, Hkv, block_s, D];
    ``block_tables``: [W, max_blocks] int32 physical ids (entries past
    a lane's frontier must be in-range but are never attended);
    ``positions``: [W] int32 — lane w's query slot; it attends cache
    slots 0..positions[w] inclusive.  Returns [W, 1, H, D].
    """
    W, _, H, D = q.shape
    Hkv, block_s = k_pool.shape[1], k_pool.shape[2]
    n_rep = H // Hkv
    mb = block_tables.shape[1]
    S = mb * block_s

    def lane(kv):  # [W, MB, Hkv, bs, D] -> [W, Hkv, MB*bs, D]
        return kv.transpose(0, 2, 1, 3, 4).reshape(W, Hkv, S, D)

    k = lane(k_pool[block_tables])
    v = lane(v_pool[block_tables])
    qg = q.reshape(W, Hkv, n_rep, D)
    s = jnp.einsum(
        "whrd,whsd->whrs", qg.astype(jnp.float32), k.astype(jnp.float32)
    ) / (D ** 0.5)
    slot = jnp.arange(S, dtype=jnp.int32)
    mask = slot[None, :] <= positions[:, None].astype(jnp.int32)
    s = jnp.where(mask[:, None, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("whrs,whsd->whrd", p, v.astype(jnp.float32))
    return o.reshape(W, 1, H, D).astype(q.dtype)


def paged_flash_qualifies(block_s: int) -> bool:
    """TPU dispatch rule for the paged kernel: pool blocks are the
    kernel's S tiles, so they must be 128-lane multiples on real
    hardware; interpret mode (CPU tests) takes any size."""
    return block_s % 128 == 0 or _interpret()


def paged_flash_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_tables: jax.Array,
    positions: jax.Array,
) -> jax.Array:
    """Ragged block-table decode attention as one Pallas dispatch.

    Same contract as :func:`paged_attention_reference`.  Grid
    ``(W, max_blocks)`` with the block axis innermost (it carries the
    online-softmax scratch); BOTH the block table and the per-lane
    positions ride the scalar-prefetch channel, so the K/V index map
    resolves ``table[w, min(j, frontier_w)]`` before the DMA — each
    lane streams only its own O(position) bytes out of the shared
    pool, regardless of how long its neighbors are.  bf16/f32 pools
    only (the int8-pool variant would mirror the quant mode above)."""
    W, Lq, H, D = q.shape
    if Lq != 1:
        raise ValueError(f"paged kernel is single-token (got Lq={Lq})")
    Hkv, block_s = k_pool.shape[1], k_pool.shape[2]
    n_rep = H // Hkv
    mb = block_tables.shape[1]
    if not _HAS_PLTPU:  # pragma: no cover
        raise RuntimeError("pallas TPU support unavailable")
    if not paged_flash_qualifies(block_s):
        raise ValueError(
            f"pool block_s={block_s} is not a 128 multiple; dispatch "
            "paged_attention_reference instead"
        )
    tbl = jnp.asarray(block_tables, jnp.int32)
    pos = jnp.asarray(positions, jnp.int32).reshape((W,))

    kv_spec = pl.BlockSpec(
        (None, Hkv, block_s, D),
        lambda w, s, tbl, pos: (
            tbl[w, jnp.minimum(s, pos[w] // block_s)], 0, 0, 0
        ),
    )
    q_spec = pl.BlockSpec((1, 1, H, D), lambda w, s, tbl, pos: (w, 0, 0, 0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(W, mb),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=q_spec,
        scratch_shapes=[
            pltpu.VMEM((H, _LANES), jnp.float32),  # running max (log2)
            pltpu.VMEM((H, _LANES), jnp.float32),  # running normalizer
            pltpu.VMEM((H, D), jnp.float32),  # output accumulator
        ],
    )
    kernel = functools.partial(
        _paged_kernel,
        block_s=block_s,
        n_rep=n_rep,
        scale=1.0 / (D**0.5),
    )
    compiler_params = (
        {}
        if _interpret()
        else {
            "compiler_params": pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")
            )
        }
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((W, 1, H, D), q.dtype),
        interpret=_interpret(),
        **compiler_params,
    )(tbl, pos, q, k_pool, v_pool)
