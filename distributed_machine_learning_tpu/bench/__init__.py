"""Benchmark harnesses (weak-scaling sweep: ``bench.sweep``).

Kept import-free so ``python -m distributed_machine_learning_tpu.bench.sweep``
doesn't trip runpy's already-imported warning.
"""
