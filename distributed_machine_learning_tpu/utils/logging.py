"""Rank-0-gated logging.

The reference prints from every rank (its banner at ``part2/2a/main.py:200-203``
even prints world size/rank per worker).  Under multi-host JAX every process
runs the same program, so the idiomatic surface is: informational prints from
process 0 only, with an escape hatch for per-rank diagnostics.
"""

from __future__ import annotations

import logging
import sys


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def rank0_print(*args, all_ranks: bool = False, **kwargs) -> None:
    """print() on process 0 only (or all ranks when all_ranks=True)."""
    if all_ranks or _process_index() == 0:
        print(*args, **kwargs)
        sys.stdout.flush()


def _initialized_process_count() -> int:
    """Process count WITHOUT forcing backend initialization.

    ``jax.process_count()`` initializes (and ``lru_cache``-freezes) the
    XLA backend — called from a log record emitted before
    ``jax.distributed.initialize``, that would both break the later
    init and pin the count at 1 forever.  Multi-host is only knowable
    after distributed init anyway, so consult its global state: not
    initialized ⇒ treat as single process, touch nothing.
    """
    try:
        import jax
        from jax._src import distributed

        if getattr(distributed.global_state, "client", None) is None:
            return 1  # distributed runtime not up: single-process
        return jax.process_count()  # safe: backend already initialized
    except Exception:
        return 1


class _RankTaggedFormatter(logging.Formatter):
    """Prefixes records with the process index on multi-host runs.

    The decision is PER RECORD, not at logger creation: loggers are
    routinely created at module-import time, before
    ``jax.distributed.initialize`` — an eager ``process_count()`` check
    there reads 1 on every host and the tag would silently never
    activate (the same ordering trap the telemetry sinks solve with a
    lazy rank gate).  Single-process runs stay untagged, and a record
    emitted before distributed init never touches the backend
    (:func:`_initialized_process_count`).
    """

    def format(self, record: logging.LogRecord) -> str:
        base = super().format(record)
        if _initialized_process_count() > 1:
            return f"p{_process_index()} {base}"
        return base


def get_logger(name: str = "dml_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(_RankTaggedFormatter(
            "%(asctime)s %(name)s %(levelname)s %(message)s"
        ))
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
    # Never propagate to the root logger: an application/basicConfig
    # root handler would print every record a second time.
    logger.propagate = False
    return logger
