# dmlcheck-virtual-path: distributed_machine_learning_tpu/train/fixture.py
"""DML011 clean case: SystemExit unwinds normally (atexit + flushes
run); the sanctioned os._exit sites live in runtime/ and flush first."""


def give_up(msg):
    raise SystemExit(msg)
