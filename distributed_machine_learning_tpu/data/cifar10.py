"""CIFAR-10 without torchvision.

The reference loads CIFAR-10 through ``torchvision.datasets.CIFAR10``
(``part1/main.py:96-97``).  Torchvision's loader just unpickles the
standard "cifar-10-batches-py" payload (five 10k-image training batches +
one test batch of dicts with ``b'data'`` (N,3072) uint8 row-major CHW and
``b'labels'``).  We parse that layout directly.

Sources tried, in order:
1. a local copy under ``root`` (``cifar-10-batches-py/`` or the .tar.gz);
2. download (the reference passes ``download=True``) — gated, since this
   environment has no egress;
3. a deterministic synthetic stand-in (seeded, same shapes/dtype/label
   distribution) so every part of the framework — and the benchmark — runs
   without the dataset on disk.  Synthetic data is clearly labeled in the
   returned metadata.

Images are returned NHWC uint8 — normalization/augmentation happen on
device (see ``augment.py``), so host→device transfer ships 3 KB/image
instead of 12 KB of fp32.
"""

from __future__ import annotations

import os
import pickle
import tarfile
from dataclasses import dataclass

import numpy as np

# Reference normalization constants (part1/main.py:82-83).
CIFAR10_MEAN = np.array([125.3, 123.0, 113.9], dtype=np.float32) / 255.0
CIFAR10_STD = np.array([63.0, 62.1, 66.7], dtype=np.float32) / 255.0

_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
_DIRNAME = "cifar-10-batches-py"
_TRAIN_FILES = [f"data_batch_{i}" for i in range(1, 6)]
_TEST_FILES = ["test_batch"]


@dataclass
class Dataset:
    images: np.ndarray  # (N, 32, 32, 3) uint8, NHWC
    labels: np.ndarray  # (N,) int32
    synthetic: bool = False

    def __len__(self) -> int:
        return len(self.labels)


def _unpickle(path: str) -> dict:
    with open(path, "rb") as f:
        return pickle.load(f, encoding="bytes")


def _load_batches(batch_dir: str, files: list[str]) -> tuple[np.ndarray, np.ndarray]:
    images, labels = [], []
    for name in files:
        d = _unpickle(os.path.join(batch_dir, name))
        # (N, 3072) uint8, row-major CHW → NHWC
        imgs = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        images.append(imgs)
        labels.append(np.asarray(d[b"labels"], dtype=np.int32))
    return np.concatenate(images), np.concatenate(labels)


def _maybe_extract(root: str) -> str | None:
    batch_dir = os.path.join(root, _DIRNAME)
    if os.path.isdir(batch_dir):
        return batch_dir
    tar_path = os.path.join(root, "cifar-10-python.tar.gz")
    if os.path.isfile(tar_path):
        with tarfile.open(tar_path, "r:gz") as tar:
            tar.extractall(root)
        return batch_dir if os.path.isdir(batch_dir) else None
    return None


def _synthetic(train: bool, seed: int = 69143) -> Dataset:
    """Deterministic stand-in with CIFAR shapes and plausible statistics."""
    n = 50_000 if train else 10_000
    rng = np.random.default_rng(seed + (0 if train else 1))
    # Class-conditional means so a model can actually learn from it in tests.
    labels = rng.integers(0, 10, size=n).astype(np.int32)
    base = rng.integers(0, 256, size=(10, 32, 32, 3), dtype=np.int64)
    noise = rng.integers(-40, 41, size=(n, 32, 32, 3), dtype=np.int64)
    images = np.clip(base[labels] + noise, 0, 255).astype(np.uint8)
    return Dataset(images=images, labels=labels, synthetic=True)


def load_cifar10(
    root: str = "./data",
    train: bool = True,
    download: bool = True,
    allow_synthetic: bool = True,
) -> Dataset:
    """Load CIFAR-10, mirroring ``datasets.CIFAR10(root, train, download)``."""
    batch_dir = _maybe_extract(root) if os.path.isdir(root) else None
    if batch_dir is None and download:
        try:
            import urllib.request

            os.makedirs(root, exist_ok=True)
            tar_path = os.path.join(root, "cifar-10-python.tar.gz")
            urllib.request.urlretrieve(_URL, tar_path)  # no egress here → raises
            batch_dir = _maybe_extract(root)
        except Exception:
            batch_dir = None
    if batch_dir is not None:
        images, labels = _load_batches(
            batch_dir, _TRAIN_FILES if train else _TEST_FILES
        )
        return Dataset(images=images, labels=labels, synthetic=False)
    if allow_synthetic:
        return _synthetic(train)
    raise FileNotFoundError(
        f"CIFAR-10 not found under {root!r} and download failed; "
        "pass allow_synthetic=True for the deterministic stand-in."
    )
