"""Deterministic fault injection — prove the runtime survives, don't hope.

The reference never sees a fault it can recover from: one stalled gloo
rank deadlocks the other three forever (SURVEY.md §5), and nothing in
its 908 LoC can even *produce* a controlled failure to test against.
This module is the chaos half of the self-healing runtime
(`runtime/supervisor.py` is the healing half): a seedable injector that
forces each production fault class at a chosen step, so the
skip/retry/restart ladder is exercised by tests instead of trusted on
faith.

Fault classes (spec grammar ``kind@step[:arg]``, comma-separated):

- ``nan@K``       poison batch K's input with NaN → the jitted step's
                  non-finite-gradient guard must skip the update
                  (float-input pipelines only; token streams are
                  integral and cannot carry a NaN).
- ``raise@K``     raise :class:`InjectedFault` from the data iterator at
                  batch K → the retrying data path (``data/retry.py``)
                  must recreate the iterator and resume.
- ``stall@K:S``   sleep S seconds before yielding batch K → the
                  watchdog must declare a stall; the supervisor restarts
                  from the latest checkpoint.
- ``kill_ckpt@N`` die during the N-th (1-based) checkpoint save, after
                  the state dir lands but before the config file — the
                  crash window ``_is_complete`` exists for.  Default
                  raises :class:`InjectedKill` (so an in-process
                  supervisor can catch the crash boundary); ``:exit``
                  calls ``os._exit(17)`` for external supervisors.

Multi-process (gang) fault classes — the failure modes only a real
worker gang can exhibit, proven by ``runtime/coordinator.py`` +
``gang_supervise``:

- ``kill_rank@R:K``    process with rank R calls ``os._exit`` (code
                       :data:`KILL_RANK_EXIT`) right before batch K —
                       the dead-peer case that leaves the other ranks
                       blocked in a collective until the gang
                       heartbeat detector aborts them.
- ``lose_rank@R:K``    like ``kill_rank`` (exit :data:`LOSE_RANK_EXIT`)
                       but PERMANENT: the ledger entry it writes marks
                       rank R's restart budget exhausted, so the gang
                       supervisor must shrink to the survivors
                       (``gang_supervise(min_world=...)``) instead of
                       relaunching the rank — the dead-host case, not
                       the crashed-process case.

Rank targeting uses the ORIGINAL (launch-time) numbering: the gang
worker keys its injector on ``--orig-rank``, so a spec keeps aiming at
the same host after a shrink renumbers the survivors, and ledger
entries carry stable ids the supervisor reads without mapping.

- ``recover_rank@R:K`` the GROW counterpart of ``lose_rank``: at batch
                       K the previously-lost host R "comes back" — a
                       ledger entry marks R's budget recovered and a
                       join announcement (``coordinator.announce_join``)
                       lands in the gang dir, which the elastic
                       supervisor (``gang_supervise(max_world=...)``)
                       admits at the next coordinated restart/grow
                       boundary.  The dead host cannot act for itself,
                       so the fault is ACTED by whichever live process
                       holds CURRENT rank 0 (exactly one exists at any
                       attempt); the ledger latch is gang-wide, so a
                       renumbered attempt never re-fires it.
- ``stall_rank@R:K:S`` rank R sleeps S seconds before batch K while
                       the others wait in the collective — the
                       stalled-peer (not dead, just stuck) case.
- ``corrupt_ckpt@N[:F]`` after the N-th (1-based) checkpoint save
                       fully commits, flip bytes in one of its saved
                       array files (the largest payload file, or the
                       first whose relative path contains substring
                       F) — bit rot the checkpoint manifest chain
                       (``train/checkpoint.py``) must catch and fall
                       back from.

``K`` may be ``?``: the step is drawn deterministically from ``seed``
(same seed → same plan), so randomized chaos runs stay reproducible.

Everything is OFF by default: an injector only exists when a spec is
given (``--faults`` or the ``DML_FAULTS`` env var), and a fault fires
exactly once.  All injection is host-side — the compiled step is never
touched; faults enter through the data stream and the checkpoint path,
the same doors real faults use.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import threading
import time

import numpy as np

from distributed_machine_learning_tpu.utils.logging import rank0_print

FAULTS_ENV = "DML_FAULTS"

# Exit code of an injected rank death — distinct from the gang abort
# code (runtime/coordinator.py::GANG_ABORT_EXIT) so a post-mortem can
# tell the victim from the ranks that detected it.
KILL_RANK_EXIT = 21

# Exit code of an injected PERMANENT rank loss (lose_rank): the rank is
# gone for good — its ledger entry marks the restart budget exhausted,
# and an elastic supervisor shrinks the gang instead of relaunching it.
LOSE_RANK_EXIT = 23

# Cross-process fired-fault ledger (one JSON line per firing), kept in
# the gang directory: a gang relaunch re-execs every worker, and without
# the ledger each fresh process would re-parse the spec and re-fire the
# same kill forever — no number of restarts could ever finish the run.
FAULT_LEDGER_FILE = "faults_fired.jsonl"

_KIND_ALIASES = {
    "nan": "nan",
    "nan_grad": "nan",
    "raise": "raise",
    "data_raise": "raise",
    "stall": "stall",
    "kill_ckpt": "kill_ckpt",
    "kill": "kill_ckpt",
    "kill_rank": "kill_rank",
    "lose_rank": "lose_rank",
    "recover_rank": "recover_rank",
    "stall_rank": "stall_rank",
    "corrupt_ckpt": "corrupt_ckpt",
    # Gray network faults (round 20): the link stays up and the rank
    # stays "alive" — only the modeled network degrades.  They act on
    # the hub-scoped NetModel (``injector.netmodel``) and are latched
    # GANG-WIDE in the ledger like recover_rank.
    "degrade_link": "degrade_link",
    "flaky_link": "flaky_link",
    "bw_collapse": "bw_collapse",
    "restore_link": "restore_link",
}

# The gray/link fault class: targets a LINK or NODE of the modeled
# network, not a process — exactly one rank acts, the mutation lives on
# the shared NetModel, and the ledger latch is gang-wide.
_LINK_KINDS = ("degrade_link", "flaky_link", "restore_link")
_GRAY_KINDS = _LINK_KINDS + ("bw_collapse",)

# Kinds whose ledger latch is GANG-WIDE on replay: the acting process
# is an assignment (rank 0, a link's src) that demotions/renumberings
# can move between hosts — a per-rank latch would let the next holder
# re-fire a fault that already happened.
_GANG_WIDE_KINDS = ("recover_rank",) + _GRAY_KINDS


class InjectedFault(RuntimeError):
    """A fault deliberately raised by the injector (data-path class)."""


class InjectedKill(InjectedFault):
    """A simulated process death mid-checkpoint.

    Raised (instead of ``os._exit``) so an in-process supervisor can
    observe the crash *boundary* — the half-written checkpoint is
    already on disk when this propagates, exactly as if the process had
    died there.
    """


@dataclasses.dataclass
class FaultEvents:
    """Counters for every robustness event — the observable surface.

    A silent recovery is indistinguishable from a bug that never
    triggered; every skip/retry/stall/restart increments a counter here,
    and ``utils/summary.py::resilience_summary`` renders the table the
    run prints.  Shared mutable state between the loop, the loaders, the
    watchdog, and the supervisor (all same-thread or GIL-atomic
    ``+= 1`` updates).
    """

    skipped_steps: int = 0      # non-finite-gradient guard skipped the update
    scaler_backoffs: int = 0    # dynamic loss scale halved on overflow
    scaler_growths: int = 0     # dynamic loss scale doubled after good steps
    loader_retries: int = 0     # data iterator recreated after an exception
    skipped_batches: int = 0    # batch dropped after exhausting its attempts
    stalls: int = 0             # watchdog declared a stall episode
    restarts: int = 0           # supervisor restored a checkpoint and retried
    preemptions: int = 0        # SIGTERM turned into a clean checkpointed stop
    ckpt_kills: int = 0         # injected death mid-checkpoint-save
    rank_kills: int = 0         # injected hard rank death (kill_rank)
    rank_losses: int = 0        # injected PERMANENT rank loss (lose_rank)
    rank_recoveries: int = 0    # injected rank recovery (recover_rank)
    rank_stalls: int = 0        # injected rank stall (stall_rank)
    ckpt_corruptions: int = 0   # injected post-save byte flips (corrupt_ckpt)
    peer_failures: int = 0      # gang detector declared a dead/stalled peer
    stragglers: int = 0         # advisory: rank flagged slow vs gang median
    gang_restarts: int = 0      # gang supervisor relaunched all workers
    gang_shrinks: int = 0       # gang continued at a smaller world size
    gang_grows: int = 0         # gang continued at a LARGER world size
    spare_promotions: int = 0   # warm spare promoted to a live rank
    spare_demotions: int = 0    # live rank demoted to warm spare
    reshard_restores: int = 0   # checkpoint restored onto a different world
    ckpt_verify_failures: int = 0  # checkpoint failed manifest verification
    ckpt_fallbacks: int = 0     # restore fell back past an invalid checkpoint
    transport_retries: int = 0  # gang-transport ops re-attempted (backoff)
    transport_timeouts: int = 0  # gang-transport ops that timed out/dropped
    replica_evictions: int = 0  # serving replicas evicted (dead or slow)
    drains: int = 0             # serving replicas drained gracefully
    request_rejects: int = 0    # serving requests rejected at admission
    weight_swaps: int = 0       # replica weight hot-swaps committed
    canary_promotions: int = 0  # deploys promoted fleet-wide (clean canary)
    canary_rollbacks: int = 0   # deploys rolled back (regression/SLO burn)
    link_degradations: int = 0  # injected gray link slowdown (degrade_link)
    link_flakes: int = 0        # injected lossy link (flaky_link)
    bw_collapses: int = 0       # injected node bandwidth collapse
    link_restorations: int = 0  # gray link state cleared (restore_link)

    def __setattr__(self, name: str, value) -> None:
        # Mirror every increment into the telemetry registry AS IT
        # HAPPENS (``fault_events{kind=...}`` counters) — the end-of-run
        # summary shows totals, but a restart wipes this object's host
        # memory while the streamed registry survives; catching the
        # write here instruments every `events.x += 1` site at once.
        prev = self.__dict__.get(name)
        object.__setattr__(self, name, value)
        if isinstance(prev, int) and isinstance(value, int) and value > prev:
            from distributed_machine_learning_tpu.telemetry import (
                get_telemetry,
            )

            tel = get_telemetry()
            if tel is not None:
                tel.registry.counter("fault_events", kind=name).inc(
                    value - prev
                )
                tel.tracer.instant(f"fault_{name}")
                # Export NOW: the next thing after some of these events
                # is a process death (kill_ckpt's os._exit mode) — a
                # counter only in host memory at that point is lost,
                # and the re-exec would rehydrate stale totals.  Fault
                # events are rare; two atomic file writes each is
                # noise.
                tel.flush()

    def as_dict(self) -> dict[str, int]:
        return dataclasses.asdict(self)

    def total(self) -> int:
        return sum(self.as_dict().values())


@dataclasses.dataclass
class _Fault:
    kind: str
    at: int            # batch index (data faults) / save ordinal (*_ckpt)
    arg: str | None = None
    rank: int | None = None  # target process (rank-aimed) / link SRC (gray)
    dst: int | None = None   # link DST (degrade/flaky/restore_link only)
    node: int | None = None  # target node (bw_collapse only)
    fired: bool = False
    index: int = -1    # position in the spec (the ledger's stable key)


class FaultInjector:
    """Parses a fault spec and fires each fault exactly once.

    One injector instance spans a whole supervised run — restarts and
    data-path replays cross the same indices again, and the fired-once
    latch is what keeps a recovered fault from re-firing forever.

    ``rank``: this process's rank for the rank-targeted fault classes;
    None (the default) reads ``jax.process_index()`` lazily at fire
    time, so every worker of a gang can parse the same spec and only
    the targeted one fires.
    """

    def __init__(self, faults: list[_Fault], rank: int | None = None):
        self._faults = faults
        for i, f in enumerate(self._faults):
            f.index = i
        self._saves = 0
        self._post_saves = 0
        self._ledger_path: str | None = None
        self._ledger_transport = None
        self.rank = rank
        # Seams for in-proc gangs (runtime/inproc_worker.py): a thread
        # rank cannot os._exit (that kills every OTHER rank too) and
        # must sleep interruptibly so a drain can collect it.  The
        # subprocess defaults are the historical behavior.
        self.exit_fn = os._exit
        self.sleep_fn = time.sleep
        # CURRENT-numbering rank (set by elastic gang workers; shrinks
        # renumber it while ``rank`` stays the original identity).
        # Only recover_rank consults it: the recovered host cannot act
        # for itself, so the fault is acted by whichever live process
        # currently holds rank 0.
        self.current_rank: int | None = None
        # Gray-fault seam (round 20): the shared
        # ``runtime/netmodel.py::NetModel`` the link fault class
        # mutates.  Hub-scoped in the in-proc gang (a relaunch clears
        # beats, not physics), None when no modeled network is attached
        # — firing a link fault then is a spec error, raised loudly.
        self.netmodel = None

    def _process_rank(self) -> int:
        if self.rank is not None:
            return self.rank
        import jax

        return jax.process_index()

    def attach_ledger(self, path_or_transport) -> "FaultInjector":
        """Make the fired-once latch survive process relaunches: every
        firing appends a line here, and attaching replays the lines —
        faults THIS RANK already fired stay fired in the fresh process.
        (Only the acting rank is latched from the ledger: other ranks
        never act on those entries anyway, and per-rank fault state —
        e.g. each rank's own save ordinals — must not cross ranks.)

        Accepts a ledger file path (the historical file backend) or a
        ``runtime/transport.py::GangTransport`` — the pluggable control
        plane carries the ledger as a channel (``append_fault_entry`` /
        ``read_fault_entries``), with identical replay semantics."""
        if hasattr(path_or_transport, "append_fault_entry"):
            self._ledger_transport = path_or_transport
            entries = path_or_transport.read_fault_entries()
        else:
            self._ledger_path = os.fspath(path_or_transport)
            entries = ledger_entries(self._ledger_path)
        me = self._process_rank()
        for entry in entries:
            i = entry.get("index")
            if not (isinstance(i, int) and 0 <= i < len(self._faults)
                    and entry.get("kind") == self._faults[i].kind):
                continue
            # recover_rank and the gray/link class latch GANG-WIDE: the
            # acting process is an assignment (rank 0, a link's src)
            # that a grow or demotion can move between hosts — a
            # per-rank latch would let the next holder re-fire a fault
            # that already happened (for a link fault, re-degrading a
            # link the campaign already consumed).
            if (entry.get("rank") == me
                    or self._faults[i].kind in _GANG_WIDE_KINDS):
                self._faults[i].fired = True
        return self

    def _has_ledger(self) -> bool:
        return (self._ledger_path is not None
                or self._ledger_transport is not None)

    def _mark_fired(self, f: _Fault, acted: bool = True) -> None:
        """Latch ``f``; when this process actually ACTED on it (not just
        observed a non-target rank's index pass by), persist the firing
        to the ledger — fsynced before returning, because the very next
        statement may be ``os._exit``."""
        f.fired = True
        if not acted or not self._has_ledger():
            return
        entry = {"index": f.index, "kind": f.kind, "at": f.at,
                 "rank": self._process_rank(), "time": time.time()}
        if f.rank is not None:
            # The TARGET of a rank-aimed fault, distinct from the
            # acting rank — for kill/lose/stall the two coincide, for
            # recover_rank they cannot (the target is the dead host).
            entry["target"] = f.rank
        if f.dst is not None:
            entry["dst"] = f.dst
        if f.node is not None:
            entry["node"] = f.node
        if self._ledger_transport is not None:
            self._ledger_transport.append_fault_entry(entry)
            return
        from distributed_machine_learning_tpu.runtime.transport import (
            append_jsonl_fsync,
        )

        append_jsonl_fsync(self._ledger_path, entry)

    # -- construction ---------------------------------------------------
    @classmethod
    def parse(cls, spec: str, seed: int = 0, horizon: int = 40,
              rank: int | None = None) -> "FaultInjector":
        """``"nan@2,raise@4,stall@7:2.5,kill_ckpt@1,kill_rank@1:7"`` →
        injector.  Gray network faults (round 20):
        ``"degrade_link@3-4:2:50,flaky_link@0-1:3:0.5,bw_collapse@1:4:8,
        restore_link@3-4:6"``.

        ``?`` steps draw from ``default_rng(seed)`` in ``[1, horizon)``,
        in spec order — deterministic per (spec, seed).
        """
        if horizon < 2:
            raise ValueError(f"horizon must be >= 2, got {horizon}")
        rng = np.random.default_rng(seed)

        def parse_at(at_s: str, entry: str) -> int:
            at_s = at_s.strip()
            if at_s == "?":
                return int(rng.integers(1, horizon))
            try:
                at = int(at_s)
            except ValueError:
                raise ValueError(
                    f"bad fault step {at_s!r} in {entry!r} (an integer "
                    "or '?')"
                ) from None
            if at < 0:
                raise ValueError(f"fault step must be >= 0, got {at}")
            return at

        faults = []
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            if "@" not in entry:
                raise ValueError(
                    f"bad fault entry {entry!r}: expected kind@step[:arg]"
                )
            kind, _, rest = entry.partition("@")
            kind = kind.strip()
            if kind not in _KIND_ALIASES:
                raise ValueError(
                    f"unknown fault kind {kind!r}; known: "
                    f"{sorted(set(_KIND_ALIASES))}"
                )
            kind = _KIND_ALIASES[kind]
            if kind in ("kill_rank", "lose_rank", "recover_rank",
                        "stall_rank"):
                # Rank-targeted grammar: kind@RANK:STEP[:ARG].
                parts = [p.strip() for p in rest.split(":")]
                want = 3 if kind == "stall_rank" else 2
                if len(parts) != want:
                    raise ValueError(
                        f"bad {kind} entry {entry!r}: expected "
                        + (f"{kind}@rank:step" if want == 2
                           else f"{kind}@rank:step:seconds")
                    )
                try:
                    target = int(parts[0])
                except ValueError:
                    raise ValueError(
                        f"bad {kind} rank {parts[0]!r} in {entry!r}"
                    ) from None
                if target < 0:
                    raise ValueError(
                        f"{kind} rank must be >= 0, got {target}"
                    )
                at = parse_at(parts[1], entry)
                arg = parts[2] if want == 3 else None
                if kind == "stall_rank":
                    float(arg)  # validate at parse time
                faults.append(
                    _Fault(kind=kind, at=at, arg=arg, rank=target)
                )
                continue
            if kind in _GRAY_KINDS:
                # Gray-network grammar (round 20):
                #   degrade_link@SRC-DST:STEP:K   latency ×K
                #   flaky_link@SRC-DST:STEP:P     loss prob → ×1/(1−P)
                #   restore_link@SRC-DST:STEP     clear both
                #   bw_collapse@NODE:STEP:K       node bandwidth ÷K
                parts = [p.strip() for p in rest.split(":")]
                want = 2 if kind == "restore_link" else 3
                if len(parts) != want:
                    raise ValueError(
                        f"bad {kind} entry {entry!r}: expected "
                        + (f"{kind}@src-dst:step" if want == 2 else
                           (f"{kind}@node:step:k" if kind == "bw_collapse"
                            else f"{kind}@src-dst:step:arg"))
                    )
                arg = parts[2] if want == 3 else None
                if arg is not None:
                    val = float(arg)  # validate at parse time
                    if kind == "flaky_link" and not 0.0 <= val <= 0.99:
                        raise ValueError(
                            f"flaky_link probability must be in "
                            f"[0, 0.99], got {arg!r} in {entry!r}")
                    if kind != "flaky_link" and val < 1.0:
                        raise ValueError(
                            f"{kind} factor must be >= 1, got {arg!r} "
                            f"in {entry!r}")
                at = parse_at(parts[1], entry)
                if kind == "bw_collapse":
                    try:
                        node = int(parts[0])
                    except ValueError:
                        raise ValueError(
                            f"bad bw_collapse node {parts[0]!r} in "
                            f"{entry!r}") from None
                    if node < 0:
                        raise ValueError(
                            f"bw_collapse node must be >= 0, got {node}")
                    faults.append(_Fault(kind=kind, at=at, arg=arg,
                                         node=node))
                    continue
                m = re.fullmatch(r"(\d+)\s*-\s*(\d+)", parts[0])
                if not m:
                    raise ValueError(
                        f"bad {kind} link {parts[0]!r} in {entry!r}: "
                        "expected SRC-DST (two rank ids)")
                src, dst = int(m.group(1)), int(m.group(2))
                if src == dst:
                    raise ValueError(
                        f"{kind} link must join two distinct ranks, "
                        f"got {src}-{dst}")
                faults.append(_Fault(kind=kind, at=at, arg=arg,
                                     rank=src, dst=dst))
                continue
            at_s, _, arg = rest.partition(":")
            at = parse_at(at_s, entry)
            arg = arg.strip() or None
            if kind == "stall":
                float(arg if arg is not None else _default_stall(None))
            if kind in ("kill_ckpt", "corrupt_ckpt"):
                if at < 1:
                    raise ValueError(
                        f"{kind} ordinal is 1-based (the first save is 1)"
                    )
                if kind == "kill_ckpt" and arg not in (None, "exit"):
                    raise ValueError(
                        f"kill_ckpt arg must be 'exit' or absent, got {arg!r}"
                    )
            faults.append(_Fault(kind=kind, at=at, arg=arg))
        return cls(faults, rank=rank)

    @classmethod
    def from_flags(cls, spec: str | None, seed: int = 0, horizon: int = 40,
                   rank: int | None = None) -> "FaultInjector | None":
        """Injector from an explicit spec, else the ``DML_FAULTS`` env
        var, else None (the default: no injection machinery at all)."""
        spec = spec or os.environ.get(FAULTS_ENV)
        if not spec:
            return None
        return cls.parse(spec, seed=seed, horizon=horizon, rank=rank)

    # -- data-path faults ----------------------------------------------
    def wrap_batches(self, batches, events: FaultEvents | None = None,
                     start: int = 0):
        """Wrap a batch iterator; data faults fire at absolute index
        ``start + j``.  Replays (retry fast-forward, post-restart) cross
        fired indices without re-firing."""
        for j, batch in enumerate(batches):
            idx = start + j
            for f in self._faults:
                if f.fired or f.at != idx:
                    continue
                if f.kind == "recover_rank":
                    # The target is a DEAD host; the live process that
                    # currently holds rank 0 acts on its behalf (every
                    # other rank just latches).  Exactly one current
                    # rank 0 exists per attempt, and the gang-wide
                    # ledger latch keeps renumbered relaunches from
                    # re-firing it.
                    cur = (self.current_rank if self.current_rank
                           is not None else self._process_rank())
                    if cur != 0:
                        self._mark_fired(f, acted=False)
                        continue
                    if events is not None:
                        events.rank_recoveries += 1
                    self._mark_fired(f)
                    if self._ledger_transport is not None:
                        self._ledger_transport.announce_join(
                            f.rank,
                            {"rank": int(f.rank), "spare": False,
                             "kind": "recover", "at_step": idx,
                             "time": time.time()},
                        )
                    elif self._ledger_path is not None:
                        from distributed_machine_learning_tpu.runtime.coordinator import (  # noqa: E501
                            announce_join,
                        )

                        announce_join(
                            os.path.dirname(self._ledger_path), f.rank,
                            kind="recover", at_step=idx,
                        )
                    print(
                        f"[faults] rank {f.rank} announced recovered "
                        f"(join published) at batch {idx}",
                        flush=True,
                    )
                elif f.kind in _GRAY_KINDS:
                    # Gray network faults: exactly one rank mutates the
                    # SHARED NetModel — the link's src for link faults
                    # (a rank id that survives renumbering), whoever
                    # currently holds rank 0 for the node-wide
                    # bw_collapse (the recover_rank convention).  The
                    # gang-wide ledger latch keeps a relaunched attempt
                    # from re-degrading a consumed link.
                    if f.kind == "bw_collapse":
                        cur = (self.current_rank if self.current_rank
                               is not None else self._process_rank())
                        acting = cur == 0
                    else:
                        acting = self._process_rank() == f.rank
                    if not acting:
                        self._mark_fired(f, acted=False)
                        continue
                    nm = self.netmodel
                    if nm is None:
                        raise InjectedFault(
                            f"{f.kind} fault at batch {idx} requires an "
                            "attached modeled network "
                            "(injector.netmodel is None — run under "
                            "the digital twin)")
                    val = float(f.arg) if f.arg is not None else None
                    if f.kind == "degrade_link":
                        nm.degrade_link(f.rank, f.dst, val)
                        if events is not None:
                            events.link_degradations += 1
                    elif f.kind == "flaky_link":
                        nm.flaky_link(f.rank, f.dst, val)
                        if events is not None:
                            events.link_flakes += 1
                    elif f.kind == "bw_collapse":
                        nm.bw_collapse(f.node, val)
                        if events is not None:
                            events.bw_collapses += 1
                    else:
                        nm.restore_link(f.rank, f.dst)
                        if events is not None:
                            events.link_restorations += 1
                    self._mark_fired(f)
                    self._publish_link_event(f, nm, idx)
                elif f.kind in ("kill_rank", "lose_rank", "stall_rank"):
                    # Every rank latches the fault at its index; only the
                    # targeted rank acts — so a gang sharing one spec
                    # fires it exactly once, on the right process.
                    if self._process_rank() != f.rank:
                        self._mark_fired(f, acted=False)
                        continue
                    if f.kind in ("kill_rank", "lose_rank"):
                        code = (KILL_RANK_EXIT if f.kind == "kill_rank"
                                else LOSE_RANK_EXIT)
                        if events is not None:
                            if f.kind == "kill_rank":
                                events.rank_kills += 1
                            else:
                                events.rank_losses += 1
                        # The ledger entry doubles as the rank's
                        # budget-exhausted marker for lose_rank: the
                        # supervisor reads it (ledger_lost_ranks) and
                        # shrinks instead of relaunching this rank.
                        self._mark_fired(f)
                        print(
                            f"[faults] rank {f.rank} exiting hard "
                            f"(exit {code}, "
                            f"{'permanent loss' if f.kind == 'lose_rank' else 'crash'}"
                            f") before batch {idx}",
                            flush=True,
                        )
                        self.exit_fn(code)
                    stall_s = float(f.arg)
                    if events is not None:
                        events.rank_stalls += 1
                    self._mark_fired(f)
                    print(
                        f"[faults] rank {f.rank} stalling {stall_s}s "
                        f"before batch {idx}",
                        flush=True,
                    )
                    self.sleep_fn(stall_s)
                elif f.kind == "stall":
                    self._mark_fired(f)
                    stall_s = float(f.arg) if f.arg else _default_stall(None)
                    rank0_print(
                        f"[faults] stalling {stall_s}s before batch {idx}"
                    )
                    self.sleep_fn(stall_s)
                elif f.kind == "raise":
                    self._mark_fired(f)
                    raise InjectedFault(f"injected loader fault at batch {idx}")
                elif f.kind == "nan":
                    self._mark_fired(f)
                    rank0_print(f"[faults] poisoning batch {idx} with NaN")
                    batch = _poison(batch)
            yield batch

    def _publish_link_event(self, f: _Fault, nm, idx: int) -> None:
        """Make a gray firing observable: a ``link_degraded`` /
        ``link_restored`` health-ledger event carrying the link's
        EFFECTIVE modeled parameters (what ``tools/gang_status.py``
        renders) and a ``gang_link_degraded{src,dst}`` counter."""
        if f.kind == "bw_collapse":
            src = f.node * nm.inner
            dst = (src + 1) % nm.world
        else:
            src, dst = f.rank, f.dst
        event = ("link_restored" if f.kind == "restore_link"
                 else "link_degraded")
        source = (f"{f.kind}@{f.node}:{f.at}" if f.kind == "bw_collapse"
                  else f"{f.kind}@{f.rank}-{f.dst}:{f.at}")
        if f.arg is not None:
            source += f":{f.arg}"
        params = nm.link_params(src, dst)
        tx = self._ledger_transport
        if tx is not None and hasattr(tx, "append_health_event"):
            tx.append_health_event(
                event, src=src, dst=dst, axis=params["axis"],
                latency_s=params["latency_s"],
                bytes_per_s=params["bytes_per_s"],
                latency_mult=params["latency_mult"],
                flaky_p=params["flaky_p"], bw_div=params["bw_div"],
                source=source, step=idx,
            )
        from distributed_machine_learning_tpu.telemetry import (
            get_telemetry,
        )

        tel = get_telemetry()
        if tel is not None:
            if event == "link_degraded":
                tel.registry.counter("gang_link_degraded", src=str(src),
                                     dst=str(dst)).inc()
            tel.tracer.instant(event, src=src, dst=dst, source=source)
            tel.flush()
        print(f"[faults] {source} fired at batch {idx}: link {src}→"
              f"{dst} now latency {params['latency_s'] * 1e6:.1f}µs, "
              f"bw {params['bytes_per_s'] / 1e9:.1f} GB/s, "
              f"loss p={params['flaky_p']}", flush=True)

    # -- checkpoint faults ---------------------------------------------
    def mid_save_hook(self, events: FaultEvents | None = None):
        """Hook for ``save_checkpoint(mid_save_hook=...)`` — called after
        the state dir lands, before the config file.  Fires ``kill_ckpt``
        on its save ordinal."""

        def hook():
            self._saves += 1
            for f in self._faults:
                if f.fired or f.kind != "kill_ckpt" or f.at != self._saves:
                    continue
                self._mark_fired(f)
                if events is not None:
                    events.ckpt_kills += 1
                if f.arg == "exit":
                    rank0_print(
                        f"[faults] killing process mid-checkpoint "
                        f"(save #{self._saves})"
                    )
                    self.exit_fn(17)
                raise InjectedKill(
                    f"injected death mid-checkpoint (save #{self._saves}; "
                    "state dir written, config file not)"
                )

        return hook

    def post_save_hook(self, events: FaultEvents | None = None):
        """Hook for ``save_checkpoint(post_save_hook=...)`` — called with
        the checkpoint path after the save fully commits.  Fires
        ``corrupt_ckpt`` on its save ordinal: flips bytes in one saved
        array file (``corrupt_checkpoint_data``), simulating the bit
        rot / torn shard the manifest verification chain exists to
        catch."""

        def hook(path):
            self._post_saves += 1
            for f in self._faults:
                if (f.fired or f.kind != "corrupt_ckpt"
                        or f.at != self._post_saves):
                    continue
                self._mark_fired(f)
                target = corrupt_checkpoint_data(path, match=f.arg)
                if events is not None:
                    events.ckpt_corruptions += 1
                rank0_print(
                    f"[faults] corrupted {target} in {path} "
                    f"(save #{self._post_saves})"
                )

        return hook

    def targeted_ranks(self) -> set[int]:
        """Every rank some fault targets (kill_rank/stall_rank) — lets a
        launcher reject targets outside the gang before spawning it
        (a mistyped rank would otherwise turn a chaos run into a
        silently fault-free one).  Link faults contribute BOTH
        endpoints — a gray link only exists between live ranks."""
        out = {f.rank for f in self._faults if f.rank is not None}
        out |= {f.dst for f in self._faults if f.dst is not None}
        return out

    def has_kind(self, kind: str) -> bool:
        """Whether the spec contains any fault of ``kind`` (fired or
        not) — lets callers reject configurations where that fault
        class could never fire (e.g. kill_ckpt under --async-ckpt)."""
        kind = _KIND_ALIASES.get(kind, kind)
        return any(f.kind == kind for f in self._faults)

    def pending(self) -> list[str]:
        """Human-readable unfired faults (for the run banner)."""
        out = []
        for f in self._faults:
            if f.fired:
                continue
            if f.dst is not None:
                head = f"{f.kind}@{f.rank}-{f.dst}:{f.at}"
            elif f.node is not None:
                head = f"{f.kind}@{f.node}:{f.at}"
            elif f.rank is not None:
                head = f"{f.kind}@{f.rank}:{f.at}"
            else:
                head = f"{f.kind}@{f.at}"
            out.append(head + (f":{f.arg}" if f.arg else ""))
        return out


def _default_stall(_) -> float:
    return 2.0


def ledger_entries(path: str | os.PathLike) -> list[dict]:
    """Every parseable firing recorded in a fired-fault ledger (absent
    file = empty; a torn final line — a kill mid-append — is skipped,
    matching ``attach_ledger``)."""
    try:
        with open(os.fspath(path)) as f:
            lines = f.read().splitlines()
    except OSError:
        return []
    out = []
    for line in lines:
        try:
            entry = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(entry, dict):
            out.append(entry)
    return out


def lost_ranks_from_entries(entries: list[dict]) -> set[int]:
    """Ranks whose ``lose_rank`` fault has fired, from parsed ledger
    entries (any transport backend)."""
    return {
        int(e["rank"]) for e in entries
        if e.get("kind") == "lose_rank" and isinstance(e.get("rank"), int)
    }


def recovered_ranks_from_entries(entries: list[dict]) -> set[int]:
    """Ranks whose ``recover_rank`` fault has fired, from parsed ledger
    entries — rank ids are the ``target`` field (ORIGINAL numbering):
    the acting process is a different, live rank."""
    return {
        int(e["target"]) for e in entries
        if e.get("kind") == "recover_rank"
        and isinstance(e.get("target"), int)
    }


def unrecovered_lost_from_entries(entries: list[dict]) -> set[int]:
    """Ranks currently lost, ORDER-AWARE: a ``recover_rank`` clears
    only the ``lose_rank`` entries appended BEFORE it.  Plain set
    subtraction would let one all-time recovery mask every later loss
    of the same rank — a host that dies again after recovering must
    count as lost again.  The ledger is append-only, so entry order is
    event order."""
    lost: set[int] = set()
    for e in entries:
        kind = e.get("kind")
        if kind == "lose_rank" and isinstance(e.get("rank"), int):
            lost.add(int(e["rank"]))
        elif kind == "recover_rank" and isinstance(e.get("target"), int):
            lost.discard(int(e["target"]))
    return lost


def ledger_lost_ranks(path: str | os.PathLike) -> set[int]:
    """Ranks whose ``lose_rank`` fault has fired, per the ledger — the
    marker the gang supervisor reads to declare a rank's restart budget
    exhausted (the fault IS the dead-host event; relaunching the rank
    would just re-lose it).  Rank ids are in the ORIGINAL numbering
    (stable across shrink renumberings — the gang worker keys its
    injector on ``--orig-rank``), so callers only intersect with the
    ranks still active."""
    return lost_ranks_from_entries(ledger_entries(path))


def ledger_recovered_ranks(path: str | os.PathLike) -> set[int]:
    """Ranks whose ``recover_rank`` fault has fired, per the ledger —
    the budget-recovered marker the elastic supervisor subtracts from
    :func:`ledger_lost_ranks` (the host came back; holding its
    ``lose_rank`` entry against it forever would make every loss
    permanent even after the recovery event)."""
    return recovered_ranks_from_entries(ledger_entries(path))


def ledger_unrecovered_lost_ranks(path: str | os.PathLike) -> set[int]:
    """File-backed form of :func:`unrecovered_lost_from_entries`."""
    return unrecovered_lost_from_entries(ledger_entries(path))


# ---------------------------------------------------------------------------
# Transport-level fault injection (ISSUE 12)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ChaosAction:
    """What the chaos plan does to ONE transport send attempt."""

    drop: bool = False        # the medium ate the request (→ retry path)
    duplicate: bool = False   # delivered twice (same op_id → dedup path)
    delay_s: float = 0.0      # delivered late
    partitioned: bool = False  # channel severed: the op cannot leave


_NO_ACTION = ChaosAction()


class TransportChaos:
    """Deterministic fault plan for a LOSSY gang transport — the tests'
    proof that the TCP retry/backoff/idempotency layer works, rather
    than an assertion that it would.

    ``drop``/``duplicate``/``delay``: iterables of ``(op, nth)`` pairs —
    fire on the nth call (1-based, counted per op kind) of that
    operation; ``op`` may be ``"*"`` to match any operation (counted
    globally).  ``delay_s`` applies to every delayed delivery.
    ``partition_after``: sever the channel entirely after N total
    operations (every later send raises, as if this member's link was
    cut) — the partitioned rank stops beating, its peers declare it
    dead within ``peer_timeout_s``, and the rank itself self-aborts
    once the outage outlives the same timeout.

    ``degrade_after`` (round 20): the GRAY counterpart of
    ``partition_after`` — after N total operations the channel goes
    slow-not-dead: every later send attempt carries
    ``degrade_delay_s`` of latency but still delivers.  This is the
    transport-level expression of ``degrade_link``: the member keeps
    beating (no peer-death escalation), it just beats late — exactly
    the failure the straggler detector, not the liveness machinery,
    must catch.

    Thread-safe: one plan is shared by a member's worker and monitor
    threads."""

    def __init__(self, *, drop=(), duplicate=(), delay=(),
                 partition_after: int | None = None,
                 delay_s: float = 0.05,
                 degrade_after: int | None = None,
                 degrade_delay_s: float = 0.05):
        self._drop = {(op, int(n)) for op, n in drop}
        self._dup = {(op, int(n)) for op, n in duplicate}
        self._delay = {(op, int(n)) for op, n in delay}
        self.partition_after = partition_after
        self.delay_s = float(delay_s)
        self.degrade_after = degrade_after
        self.degrade_delay_s = float(degrade_delay_s)
        self._counts: dict[str, int] = {}
        self._total = 0
        self._lock = threading.Lock()
        self.fired: list[tuple[str, str, int]] = []  # (action, op, nth)

    def _matches(self, plan: set, op: str, nth: int, any_nth: int) -> bool:
        return (op, nth) in plan or ("*", any_nth) in plan

    def plan(self, op: str) -> ChaosAction:
        """Called by the transport client once per SEND ATTEMPT (so a
        dropped op's retry is a fresh attempt that the plan may or may
        not hit again)."""
        with self._lock:
            self._total += 1
            self._counts[op] = self._counts.get(op, 0) + 1
            nth, any_nth = self._counts[op], self._total
            if (self.partition_after is not None
                    and self._total > self.partition_after):
                self.fired.append(("partition", op, any_nth))
                return ChaosAction(partitioned=True)
            degraded = (self.degrade_after is not None
                        and self._total > self.degrade_after)
            drop = self._matches(self._drop, op, nth, any_nth)
            dup = self._matches(self._dup, op, nth, any_nth)
            delay = self._matches(self._delay, op, nth, any_nth)
            if degraded:
                self.fired.append(("degrade", op, any_nth))
            if drop:
                self.fired.append(("drop", op, nth))
            if dup:
                self.fired.append(("duplicate", op, nth))
            if delay:
                self.fired.append(("delay", op, nth))
        if not (drop or dup or delay or degraded):
            return _NO_ACTION
        delay_s = self.delay_s if delay else 0.0
        if degraded:
            # Gray state is PERSISTENT: every attempt from here on is
            # slow — additive with a one-shot delay match.
            delay_s += self.degrade_delay_s
        return ChaosAction(drop=drop, duplicate=dup, delay_s=delay_s)


def corrupt_checkpoint_data(path: str | os.PathLike, match: str | None = None,
                            nbytes: int = 16) -> str:
    """Flip ``nbytes`` bytes in the middle of one saved array file under
    ``path/state`` — the largest payload file, or the first whose
    relative path contains ``match``.  Returns the relative path
    corrupted.  The manifest is left untouched: the point is exactly
    that the bytes on disk no longer match it."""
    path = os.fspath(path)
    state_dir = os.path.join(path, "state")
    candidates = []
    for root, _, files in os.walk(state_dir):
        for name in files:
            if name.startswith("_"):
                continue  # metadata; payload lives in the d/ dirs
            fp = os.path.join(root, name)
            rel = os.path.relpath(fp, path)
            if match and match not in rel:
                continue
            candidates.append((os.path.getsize(fp), rel, fp))
    if not candidates:
        raise FileNotFoundError(
            f"no array file to corrupt under {state_dir}"
            + (f" matching {match!r}" if match else "")
        )
    size, rel, fp = max(candidates)
    offset = max(0, size // 2 - nbytes // 2)
    with open(fp, "r+b") as f:
        f.seek(offset)
        chunk = f.read(min(nbytes, max(size - offset, 1)))
        f.seek(offset)
        f.write(bytes(b ^ 0xFF for b in chunk))
    # The simulated rot must also invalidate the GC validation memo —
    # otherwise GC would keep trusting the pre-flip hash and could
    # anchor the keep window on the garbage this fault just created.
    from distributed_machine_learning_tpu.train.checkpoint import (
        forget_validated,
    )

    forget_validated(path)
    return rel


def _poison(batch):
    """Replace the float-able input of an ``(x, y)`` batch with NaN.

    The poisoned array rides the normal host→device path; ``normalize``
    accepts float input, so NaN propagates through loss and gradients —
    the blowup the guard must catch.  Integer token streams cannot carry
    a NaN; that pipeline's guard is unit-tested at the step level
    instead (``tests/test_resilience.py``).
    """
    x, *rest = batch
    x = np.asarray(x)
    if not np.issubdtype(x.dtype, np.floating) and not np.issubdtype(
        x.dtype, np.integer
    ):
        raise TypeError(f"cannot poison batch of dtype {x.dtype}")
    if np.issubdtype(x.dtype, np.integer) and x.ndim < 3:
        raise TypeError(
            "refusing to poison what looks like an integer token/label "
            "array (the model indexes with it); nan faults need a "
            "float-able input pipeline like the CNN image path"
        )
    poisoned = np.full(x.shape, np.nan, np.float32)
    return (poisoned, *rest)
