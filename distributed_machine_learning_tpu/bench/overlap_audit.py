"""Ring-bucket comm/compute overlap audit — schedule-level proof.

The north-star program (``ops/ring.py``) claims XLA's async collective
scheduler overlaps bucket k's ppermutes with bucket k+1's adds — the
property DDP's C++ reducer provides and the reason 25 MB buckets exist
(``/root/reference/part3/main.py:59``, group25.pdf p.6).  A single
attached chip cannot *run* an 8-device ring (a 1-device mesh has zero
ppermutes), so this audit produces the strongest evidence available
without a pod: it AOT-compiles the full part3 train step for a REAL
multi-chip TPU target (``jax.experimental.topologies`` — the same
XLA:TPU backend, latency-hiding scheduler included, that a pod would
use) and walks the optimized module's schedule:

- every ``collective-permute-start``/``-done`` pair is an async window
  in which the DMA is in flight;
- compute ops textually scheduled between start and done execute under
  that DMA — the overlap, read straight off the executable.

Run: ``python -m distributed_machine_learning_tpu.bench.overlap_audit``
(needs libtpu for the compile-only TPU client; prints one JSON line).

This is a static schedule, not a device timeline: it proves the
executable *orders* bucket math under bucket DMAs, while actual wall-
clock hiding additionally depends on DMA latency vs fusion runtime —
the part a pod xprof would add.
"""

from __future__ import annotations

import collections
import json
import re


def audit_schedule(hlo_text: str) -> dict:
    """Walk an optimized, scheduled HLO module; report per-async-window
    compute.  Returns a JSON-able summary dict."""
    m = re.search(r"ENTRY [^\{]+\{(.*?)\n\}", hlo_text, re.S)
    if not m:
        raise ValueError("no ENTRY computation found in HLO text")
    start_re = re.compile(r"%?(\S+) = .* collective-permute-start\(")
    done_re = re.compile(r"collective-permute-done\(.*?%?([\w\.\-]+)\)")
    compute_re = re.compile(
        r"%?(\S+) = .*?(fusion|convolution|dot|all-reduce(?!-)|"
        r"reduce-scatter)\("
    )
    open_pairs: dict[str, list] = {}
    in_flight, max_in_flight = 0, 0
    windows = []
    for line in m.group(1).splitlines():
        s = start_re.search(line)
        if s:
            open_pairs[s.group(1)] = []
            in_flight += 1
            max_in_flight = max(max_in_flight, in_flight)
            continue
        d = done_re.search(line)
        if d and d.group(1) in open_pairs:
            windows.append((d.group(1), open_pairs.pop(d.group(1))))
            in_flight -= 1
            continue
        c = compute_re.search(line)
        if c:
            for ops in open_pairs.values():
                ops.append((c.group(1), c.group(2)))
    # An op inside two concurrently-open windows counts once: the
    # metric is "distinct compute ops that execute under some in-flight
    # DMA", not a per-window tally.
    unique_ops = {name: kind for _, ops in windows for name, kind in ops}
    kinds = collections.Counter(unique_ops.values())
    return {
        "async_ppermute_pairs": len(windows),
        "pairs_with_compute_in_window": sum(1 for _, o in windows if o),
        "distinct_compute_ops_in_windows": len(unique_ops),
        "op_kinds_in_windows": dict(kinds),
        "max_concurrent_in_flight": max_in_flight,
    }


def compile_part3_for_topology(topology_name: str = "v5e:2x4",
                               global_batch: int = 256) -> str:
    """AOT-compile the part3 ring train step (VGG-11+BN, 25 MB buckets)
    for a multi-chip TPU topology; return the optimized HLO text."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh

    from distributed_machine_learning_tpu.cli.common import (
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.models.vgg import VGG11
    from distributed_machine_learning_tpu.parallel.strategies import (
        get_strategy,
    )
    from distributed_machine_learning_tpu.train.step import make_train_step

    topo = topologies.get_topology_desc(
        platform="tpu", topology_name=topology_name
    )
    devs = np.array(topo.devices)
    mesh = Mesh(devs.reshape(devs.size), ("batch",))
    model = VGG11(use_bn=True, compute_dtype=jnp.bfloat16)
    state_shape = jax.eval_shape(lambda: init_model_and_state(model))
    x = jax.ShapeDtypeStruct((global_batch, 32, 32, 3), jnp.float32)
    y = jax.ShapeDtypeStruct((global_batch,), jnp.int32)
    step = make_train_step(model, get_strategy("ring"), mesh=mesh)
    return step.lower(state_shape, x, y).compile().as_text()


def main() -> None:
    summary = audit_schedule(compile_part3_for_topology())
    summary["metric"] = "ring_overlap_audit_v5e_2x4"
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
