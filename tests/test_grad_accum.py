"""Gradient accumulation: the accumulated update must equal the
full-batch update exactly (BN-free, augmentation off — the two sources of
intentional per-microbatch variation)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.cli.common import init_model_and_state
from distributed_machine_learning_tpu.models.vgg import VGGTest
from distributed_machine_learning_tpu.parallel.strategies import get_strategy
from distributed_machine_learning_tpu.runtime.mesh import make_mesh
from distributed_machine_learning_tpu.train.step import (
    make_train_step,
    shard_batch,
)


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(3)
    x = rng.integers(0, 256, (16, 32, 32, 3), dtype=np.uint8)
    y = rng.integers(0, 10, 16).astype(np.int32)
    return x, y


def _params_close(a, b, **kw):
    for la, lb in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), **kw)


@pytest.mark.parametrize("accum", [2, 4])
def test_accum_matches_full_batch(data, accum):
    x, y = data
    model = VGGTest()

    full = make_train_step(model, augment=False)
    s_full, loss_full = full(init_model_and_state(model), x, y)

    acc = make_train_step(model, augment=False, accum_steps=accum)
    s_acc, loss_acc = acc(init_model_and_state(model), x, y)

    np.testing.assert_allclose(float(loss_acc), float(loss_full), rtol=1e-6)
    _params_close(s_full.params, s_acc.params, rtol=1e-5, atol=1e-7)


def test_accum_on_mesh_matches(data):
    """accum composes with the distributed step: 8-way DP x 2-way accum
    equals the single-device full-batch step."""
    x, y = data
    model = VGGTest()
    mesh = make_mesh(8)

    full = make_train_step(model, augment=False)
    s_full, loss_full = full(init_model_and_state(model), x, y)

    # The ring strategy averages over the axis (part3 semantics), so
    # 8-way DP x 2-way accum must reproduce the full-batch update exactly.
    step_ring = make_train_step(
        model, get_strategy("ring"), mesh=mesh, augment=False, accum_steps=2
    )
    mx, my = shard_batch(mesh, x, y)
    s_ring, loss_ring = step_ring(init_model_and_state(model), mx, my)
    np.testing.assert_allclose(float(loss_ring), float(loss_full), rtol=1e-5)
    _params_close(s_full.params, s_ring.params, rtol=1e-4, atol=1e-6)


def test_accum_with_bn_stays_finite(data):
    """BN models accumulate too (stats update per microbatch) — smoke."""
    x, y = data
    model = VGGTest(use_bn=True)
    step = make_train_step(model, augment=False, accum_steps=4)
    state, loss = step(init_model_and_state(model), x, y)
    assert np.isfinite(float(loss))
    for leaf in jax.tree_util.tree_leaves(state.batch_stats):
        assert np.isfinite(np.asarray(leaf)).all()


def test_accum_validates():
    model = VGGTest()
    with pytest.raises(ValueError, match="accum_steps"):
        make_train_step(model, accum_steps=0)
    step = make_train_step(model, augment=False, accum_steps=3)
    x = np.zeros((16, 32, 32, 3), np.uint8)
    y = np.zeros((16,), np.int32)
    with pytest.raises(ValueError, match="not divisible"):
        step(init_model_and_state(model), x, y)
