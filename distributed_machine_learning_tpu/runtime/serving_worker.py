"""Serving replica worker — the fleet's rank-side loop (ISSUE 16).

One worker = one rank's thread (in-proc campaigns) or process
(``cli/serve.py --role worker`` over tcp/file), driving the replica
state machine the router controls through the serving channels:

``spare``: announce on the join channel (``spare=True``, refreshed
every heartbeat, with the newest prefetched checkpoint step — the PR
10 warm-spare contract, so promotion is O(restore) not O(init)) and
poll ``read_serving`` for a promotion.

``live``: publish beats (liveness + the last micro-batch service time
the router's straggler detector judges), pop micro-batches off this
rank's request queue, run the injected ``step_fn`` (production: the
``inference/generate.py`` step-callable seam,
``make_serving_step``), and post one result per request **under the
serving epoch bound at promotion**.  When the router retires this
replica (drain completed, or eviction) the epoch advances: the
worker's next ``read_serving`` shows a new epoch/role and it falls
back to spare mode — and any result it was still holding posts as a
fenced no-op (``post_result`` → False), never a duplicate.  Requests
carry their dispatch epoch: a taken request stamped NEWER than the
bound epoch (this rank was retired and re-promoted between the
worker's serving read and its take) is pushed back and the worker
rebinds before serving it, so no request is burned under a fence that
is guaranteed to reject it.

The loop mirrors ``runtime/inproc_worker.py``: ``TransportError``
means this worker is severed from the control plane (hub cleared, tcp
partition) and it retires quietly — the router's beat-staleness
eviction re-dispatches whatever it owned.  This module is
deliberately jax-free: ``step_fn`` is the only compute seam.
"""

from __future__ import annotations

import dataclasses
import threading
import time

from distributed_machine_learning_tpu.runtime.transport import (
    GangTransport,
    TransportError,
    carry_stage_context,
    stamp_stage,
)


@dataclasses.dataclass
class ServingWorkerConfig:
    heartbeat_interval: float = 0.05  # beat + spare-announce cadence
    micro_batch: int = 4              # max requests per take
    poll_s: float = 0.005             # idle request-poll cadence


def run_serving_worker(tx: GangTransport, rank: int, step_fn,
                       stop_event: threading.Event,
                       cfg: ServingWorkerConfig | None = None, *,
                       prefetch_fn=None, on_restore=None,
                       on_swap=None, telemetry=None,
                       engine=None) -> dict:
    """Drive one replica until ``stop_event`` (a campaign's kill switch
    doubles as the worker's death) or the control plane severs.

    ``step_fn(prompts) -> outputs``: the compute seam — one output per
    prompt, order-aligned.  ``prefetch_fn() -> int | None``: called
    while spare, returns the newest verified checkpoint step to
    advertise.  ``on_restore(prefetched_step)``: called once per
    promotion — where a real replica restores params (O(restore));
    tests count the calls.  ``on_swap(version, record) -> step_fn |
    None``: the weight hot-swap seam (ISSUE 18) — called between
    micro-batches when the deploy controller staged a new weights
    version for this rank (``set_weights``), AFTER every in-flight
    result posted under the old version (the drain).  It loads the
    staged weights (production: rebuild the ``make_serving_step``
    callable from the record's checkpoint path) and may return a
    replacement step function; the worker then ``commit_weights`` —
    the hub-atomic fence move — and serves every later request under
    the new version.  ``telemetry``: this replica's own
    instance-tagged :class:`~..telemetry.Telemetry` — one ``request``
    span per take→outcome lands in its Chrome trace, which
    ``tools/trace_merge.py`` re-homes next to the router's track.

    Requests that carry an ``events`` record (ISSUE 17) are stamped at
    every stage on THIS replica's monotonic clock: ``taken`` (in the
    transport wrapper), ``bound`` after the fence check, ``computed``
    after ``step_fn``, ``posted`` at the post (wrapper again) — and on
    the failure paths ``requeued`` (newer-epoch repush) / ``fenced``
    (zombie drop), so every exit closes the record.

    ``engine`` (ISSUE 19): a
    :class:`~..inference.continuous.ContinuousEngine` replaces the
    batch-static ``step_fn`` with iteration-level scheduling.  Fence
    triage is unchanged; kept requests are *submitted* to the engine
    (which stamps ``prefill``/``decode`` instead of ``computed``),
    each loop iteration advances it one decode step, and every
    retirement posts immediately under the bound epoch — so requests
    finish mid-micro-batch instead of waiting on the group.  Router
    lever hints (``request["lever"]``, stamped by a regime-aware
    dispatcher) are forwarded via ``note_lever``.  On a staged weight
    version the worker pauses admission and keeps stepping until
    ``engine.in_flight() == 0`` — the drain that guarantees no
    sequence ever mixes weight versions — before ``on_swap`` (which
    may return a *replacement engine*, or swap the existing engine's
    params itself and return None) and the ``commit_weights`` fence.
    On retirement the engine's queued/in-flight work is aborted
    without posting: the router's retire already requeued those rids
    for survivors, and a late post would fence anyway.

    Returns a summary dict (served counts, restores) for audits.
    """
    cfg = cfg or ServingWorkerConfig()
    tracer = telemetry.tracer if telemetry is not None else None
    by = f"replica{rank}"
    seq = 0
    served = 0
    fenced = 0
    repushed = 0
    restores = 0
    swaps = 0
    aborted = 0
    last_service: float | None = None
    bound_epoch: int | None = None
    bound_version: int | None = None
    prefetched = None
    last_announce = -1.0
    last_beat = -1.0
    if engine is not None:
        # The engine stamps stage events itself; rename its actor to
        # this replica so each request's taken→bound→prefill→decode
        # chain stays on one monotonic clock (and the router's
        # straggler feed can attribute the decode samples to a rank).
        engine._by = by

    def _post_engine(done) -> None:
        """Post one engine retirement batch under the bound epoch."""
        nonlocal served, fenced, last_service
        for d in done:
            req = d.get("request") or {}
            svc = d["prefill_s"] + d["decode_s"]
            last_service = svc
            ok = tx.post_result(rank, bound_epoch,
                                carry_stage_context(req, {
                                    "rid": d["rid"],
                                    "output": d["tokens"],
                                    "service_time_s": svc,
                                    "lever": d["lever"],
                                }), version=bound_version)
            if tracer is not None:
                t1 = time.perf_counter()
                tracer.complete("request", t1 - d["e2e_s"], t1,
                                rid=d["rid"], rank=rank,
                                stage="posted" if ok else "fenced")
            if ok:
                served += 1
            else:
                fenced += 1

    try:
        while not stop_event.is_set():
            state = tx.read_serving(rank)
            if state["role"] != "live":
                if engine is not None and bound_epoch is not None:
                    # Retired with work still on the engine: the
                    # router's retire_replica already requeued every
                    # owned rid for survivors — drop ours without
                    # posting (a post would fence anyway).
                    aborted += len(engine.abort_all())
                bound_epoch = None
                bound_version = None
                now = time.monotonic()
                if (last_announce < 0
                        or now - last_announce
                        >= cfg.heartbeat_interval):
                    if prefetch_fn is not None:
                        prefetched = prefetch_fn()
                    tx.announce_join(rank, {
                        "rank": rank, "spare": True, "kind": "serving",
                        "prefetched_step": prefetched,
                        "time": time.time(),
                    })
                    last_announce = now
                stop_event.wait(cfg.poll_s)
                continue
            if bound_epoch != state["epoch"]:
                # Promoted (or re-promoted into a fresh epoch): restore
                # before serving, and post every future result under
                # THIS epoch — the fence that makes a late post after
                # retirement a no-op instead of a duplicate.
                if engine is not None and bound_epoch is not None:
                    # Re-promoted without passing through spare: the
                    # old epoch's requests were requeued at retirement.
                    aborted += len(engine.abort_all())
                bound_epoch = state["epoch"]
                bound_version = None  # rebind to the committed record
                restores += 1
                last_announce = -1.0
                if on_restore is not None:
                    on_restore(prefetched)
            wrec = state.get("weights") or {}
            if bound_version is None:
                bound_version = int(wrec.get("version", 0) or 0)
                if engine is not None and not engine.in_flight():
                    engine.version = bound_version
            pending = wrec.get("pending")
            if pending is not None and int(pending) != bound_version:
                # Hot-swap point (ISSUE 18): the deploy controller
                # staged a new weights version for this rank.  We are
                # between micro-batches here — every in-flight result
                # already posted under the OLD version, which is the
                # zero-dropped-requests drain the two-phase protocol
                # promises.  Load the staged weights, then commit: the
                # hub flips the committed version atomically with the
                # result fence, so an old-version zombie's late post
                # can never complete a post-swap rid.
                pending = int(pending)
                if engine is not None:
                    # Engine drain (ISSUE 19): sequences are mid-decode
                    # at arbitrary frontiers, and swap_params refuses
                    # while any are in flight — finish every one under
                    # the OLD weights first, admission paused so queued
                    # work waits for the new version.  This is the
                    # step-boundary fence: no sequence ever mixes
                    # weight versions mid-stream.
                    engine.pause_admission()
                    while (engine.in_flight()
                           and not stop_event.is_set()):
                        _post_engine(engine.step())
                    if engine.in_flight():
                        continue  # killed mid-drain; exit via loop top
                    if on_swap is not None:
                        new_engine = on_swap(pending, dict(wrec))
                        if new_engine is not None:
                            engine = new_engine
                            engine._by = by
                    engine.version = pending
                    engine.resume_admission()
                elif on_swap is not None:
                    new_step = on_swap(pending, dict(wrec))
                    if new_step is not None:
                        step_fn = new_step
                tx.commit_weights(rank, pending)
                bound_version = pending
                swaps += 1
                if tracer is not None:
                    tracer.instant("weight_swap", rank=rank,
                                   version=bound_version)
            now = time.monotonic()
            if last_beat < 0 or now - last_beat >= cfg.heartbeat_interval:
                seq += 1
                tx.publish_beat(rank, {
                    "rank": rank, "seq": seq, "kind": "serving",
                    "served": served, "service_time_s": last_service,
                    "weight_version": bound_version,
                    "time": time.time(),
                })
                last_beat = now
            reqs = tx.take_requests(rank, cfg.micro_batch)
            if not reqs and engine is None:
                stop_event.wait(cfg.poll_s)
                continue
            t_take = time.perf_counter()
            # Fence check BEFORE compute: the router stamps every
            # request with its dispatch epoch.  A stamp NEWER than the
            # bound means this rank was retired and re-promoted between
            # read_serving and the take — running the request under the
            # stale bound would fence its post and strand it in the new
            # replica's in-flight set forever (the rank keeps beating,
            # so no eviction requeues it): push it back onto our own
            # queue and re-read the serving state to rebind first.  A
            # stamp OLDER than the bound is a zombie from a retired
            # epoch (the router requeued its rid at retirement) — drop
            # it as fenced rather than re-push it in a cycle no future
            # epoch can ever serve.
            keep = []
            newer = []
            for r in reqs:
                e = r.get("epoch")
                if e is None or e == bound_epoch:
                    keep.append(r)
                elif e > bound_epoch:
                    newer.append(r)
                else:
                    fenced += 1
                    if isinstance(r.get("events"), list):
                        stamp_stage(r, "fenced", by, epoch=e,
                                    bound=bound_epoch)
                    if tracer is not None:
                        tracer.complete("request", t_take,
                                        time.perf_counter(),
                                        rid=r.get("rid"), rank=rank,
                                        stage="fenced")
            if newer:
                for r in newer:
                    if isinstance(r.get("events"), list):
                        stamp_stage(r, "requeued", by,
                                    epoch=r.get("epoch"),
                                    bound=bound_epoch)
                    if tracer is not None:
                        tracer.complete("request", t_take,
                                        time.perf_counter(),
                                        rid=r.get("rid"), rank=rank,
                                        stage="requeued")
                    tx.push_request(rank, r)
                repushed += len(newer)
            reqs = keep
            if engine is not None:
                for r in reqs:
                    if isinstance(r.get("events"), list):
                        # dt: taken -> bound, the fence-check interval.
                        stamp_stage(r, "bound", by, epoch=bound_epoch)
                    hint = r.get("lever")
                    if hint is not None:
                        try:
                            engine.note_lever(hint)
                        except ValueError:
                            pass  # router speaks a newer lever dialect
                    try:
                        engine.submit(r.get("rid"), r.get("prompt"),
                                      max_new=r.get("max_new"),
                                      request=r)
                    except (TypeError, ValueError) as e:
                        # A request the engine can NEVER serve (empty,
                        # or longer than max_len): answer it rather
                        # than strand it in the router's in-flight set.
                        tx.post_result(rank, bound_epoch,
                                       carry_stage_context(r, {
                                           "rid": r.get("rid"),
                                           "output": None,
                                           "error": str(e),
                                       }), version=bound_version)
                if newer and not reqs:
                    continue  # rebind via read_serving first
                if not engine.has_work():
                    stop_event.wait(cfg.poll_s)
                    continue
                # One iteration: every in-flight sequence advances one
                # token; retirements post immediately and their lanes
                # backfill inside the same step.
                _post_engine(engine.step())
                continue
            if not reqs:
                if newer:
                    continue  # rebind via read_serving first
                stop_event.wait(cfg.poll_s)
                continue
            for r in reqs:
                if isinstance(r.get("events"), list):
                    # dt: taken -> bound, the fence-check interval.
                    stamp_stage(r, "bound", by, epoch=bound_epoch)
            t0 = time.perf_counter()
            outs = step_fn([r.get("prompt") for r in reqs])
            last_service = time.perf_counter() - t0
            for r in reqs:
                if isinstance(r.get("events"), list):
                    # dt: bound -> computed, this replica's compute
                    # interval — the straggler detector's sample.
                    stamp_stage(r, "computed", by)
            for req, out in zip(reqs, outs):
                ok = tx.post_result(rank, bound_epoch,
                                    carry_stage_context(req, {
                                        "rid": req.get("rid"),
                                        "output": out,
                                        "service_time_s": last_service,
                                    }), version=bound_version)
                if tracer is not None:
                    tracer.complete("request", t_take,
                                    time.perf_counter(),
                                    rid=req.get("rid"), rank=rank,
                                    stage="posted" if ok else "fenced")
                if ok:
                    served += 1
                else:
                    # Retired mid-batch: the fence already handed the
                    # rest of this work to survivors.
                    fenced += 1
                    break
    except TransportError:
        pass  # severed from the control plane: retire quietly
    return {"rank": rank, "served": served, "fenced": fenced,
            "repushed": repushed, "restores": restores, "swaps": swaps,
            "aborted": aborted, "weight_version": bound_version}


def start_worker_thread(tx: GangTransport, rank: int, step_fn,
                        stop_event: threading.Event,
                        cfg: ServingWorkerConfig | None = None,
                        **kwargs) -> tuple[threading.Thread, dict]:
    """Spawn :func:`run_serving_worker` on a daemon thread; the second
    element collects the worker's summary once it exits (campaign
    audits read it after joining)."""
    out: dict = {}

    def _run():
        out.update(run_serving_worker(tx, rank, step_fn, stop_event,
                                      cfg, **kwargs))

    t = threading.Thread(target=_run, name=f"serve-worker-{rank}",
                         daemon=True)
    t.start()
    return t, out
