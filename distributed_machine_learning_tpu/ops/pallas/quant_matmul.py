"""Weight-only int8 matmul as a Pallas TPU kernel — the decode bandwidth lever.

Autoregressive decode is bound by HBM reads of the weights (docs/PERF.md:
the bf16 serving config sits at the weights+cache bandwidth floor), so
halving the weight bytes is a direct tokens/s multiplier.  The catch is
that XLA does NOT fuse an ``int8 → bf16`` convert into a dot operand at
these sizes: measured on this chip, ``x @ (q.astype(bf16) * scale)``
inside a decode scan runs 0.65× bf16 — the dequantized matrix
materializes in HBM, *tripling* traffic instead of halving it.  Hence
this kernel: the int8 tile is DMA'd into VMEM (half the bytes of bf16),
converted to bf16 in-register, fed to the MXU with f32 accumulation,
and scaled per output channel on the way out.  HBM never sees a
dequantized byte.

Quantization scheme (``quantize_int8``): symmetric per-output-channel —
``q = round(w / s)`` with ``s = max|w_col| / 127``, the standard
weight-only recipe (per-channel scales cost [K] floats and remove the
worst-case column error of a per-tensor scale).  Matmul error is then
~0.4% RMS relative — well under bf16 activation noise for serving.

Grid: ``(rows // bR, K // bK)`` with the full contraction depth D in
one block — at serving widths (D ≤ 8k) an int8 [D, bK=512] tile is
≤4 MB of VMEM, and one-shot dots avoid a scratch accumulator entirely.
Both grid axes are parallel (no cross-step state).  int8 VMEM tiles
need (32, 128) alignment: D and bK are validated multiples of 32/128.

Reference note: the reference has no inference or quantization surface
at all (its eval is ``test_model``, part1/main.py:62-77); this is
beyond-parity serving capability, same family as inference/generate.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from distributed_machine_learning_tpu.ops.pallas.common import (
    _interpret,
    tile_compiler_params,
)


def quantize_int8(w: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-output-channel int8 quantization of a [D, K] matrix.

    Returns ``(q int8 [D, K], scale f32 [K])`` with
    ``w ≈ q * scale[None, :]``.  An all-zero column gets scale 1 (its
    quantized values are all zero anyway — avoids 0/0).
    """
    w = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=0)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w / scale[None, :]), -127, 127).astype(jnp.int8)
    return q, scale


def _kernel(x_ref, q_ref, s_ref, o_ref):
    acc = jax.lax.dot_general(
        x_ref[...],
        q_ref[...].astype(jnp.bfloat16),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[...] = (acc * s_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


# Tiling helper hoisted to the shared kernel plumbing; the historical
# private name keeps resolving for existing callers.
from distributed_machine_learning_tpu.ops.pallas.common import (  # noqa: E402
    pick_block as _pick_block,
)


@functools.partial(jax.jit, static_argnames=("block_rows", "block_k"))
def int8_matmul(
    x: jax.Array,
    q: jax.Array,
    scale: jax.Array,
    block_rows: int | None = None,
    block_k: int | None = None,
) -> jax.Array:
    """``x @ (q * scale)`` reading the weights as int8.  [R, D] × [D, K]
    → [R, K] in ``x.dtype``; compute is bf16×bf16→f32 on the MXU.
    """
    R, D = x.shape
    D2, K = q.shape
    if D != D2 or scale.shape != (K,):
        raise ValueError(
            f"shape mismatch: x [{R},{D}], q [{D2},{K}], scale {scale.shape}"
        )
    # VMEM budget (per-buffer caps, ×2 for double buffering): the x tile
    # [bR, D] bf16 stays ≤2 MB and the q tile [D, bK] int8 ≤4 MB, so the
    # working set ≈ (2+4+ε)·2 ≈ 13 MB fits the 16 MB VMEM at any D —
    # without the caps a d_ff=8k prefill x-tile alone is 4 MB and Mosaic
    # runs out of scoped VMEM.
    r_cap = max(8, min(256, (1 << 21) // (2 * D)))
    k_cap = max(128, min(512, (1 << 22) // D))
    # Rows tile freely once R is a multiple of 8 (divisor 8 <= r_cap
    # always exists), so an awkward row count — an odd-length prefill —
    # is zero-padded here and sliced back, instead of falling through to
    # one whole-[R, D] tile that blows the VMEM budget above.
    pad_rows = 0
    if R > 8 and R % 8:
        pad_rows = 8 - R % 8
        x = jnp.pad(x, ((0, pad_rows), (0, 0)))
        R += pad_rows
    bR = block_rows or _pick_block(R, r_cap, 8) or R
    bK = block_k or _pick_block(K, k_cap, 128)
    pad_k = 0
    if bK is None and block_k is None:
        # K has no 128-multiple divisor under the cap (e.g. the fused
        # qkv of a d_model=320 model gives K=960): zero-pad the weight
        # columns and scales up to the next 128 multiple — padded
        # columns multiply to exact zeros and are sliced off below —
        # mirroring the row-padding path instead of refusing the width.
        # Inside a scanned decode program the padded weight is loop-
        # invariant and XLA hoists it (verified on the compiled HLO:
        # the s8 pad lives outside the while body, the padded array
        # rides the loop carry) — the copy costs once per program, not
        # per token.
        pad_k = (-K) % 128
        q = jnp.pad(q, ((0, 0), (0, pad_k)))
        scale = jnp.pad(scale, ((0, pad_k),))
        K += pad_k
        bK = _pick_block(K, k_cap, 128)
    if bK is None or K % bK or R % bR:
        raise ValueError(
            f"K={K} must tile by a multiple of 128 and R={R} by the row "
            f"block (got bR={bR}, bK={bK}); pad the operands"
        )
    if D % 32 and D > 32:
        raise ValueError(f"contraction depth D={D} must be a multiple of 32")
    out_dtype = x.dtype
    x = x.astype(jnp.bfloat16)
    grid = (R // bR, K // bK)
    kwargs = tile_compiler_params(("parallel", "parallel"))
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bR, D), lambda r, k: (r, 0)),
            pl.BlockSpec((D, bK), lambda r, k: (0, k)),
            pl.BlockSpec((1, bK), lambda r, k: (0, k)),
        ],
        out_specs=pl.BlockSpec((bR, bK), lambda r, k: (r, k)),
        out_shape=jax.ShapeDtypeStruct((R, K), out_dtype),
        interpret=_interpret(),
        **kwargs,
    )(x, q, scale.reshape(1, K))
    return out[: R - pad_rows, : K - pad_k] if (pad_rows or pad_k) else out
