# dmlcheck-virtual-path: distributed_machine_learning_tpu/runtime/transport.py
"""DML013 clean cases: every mutation of guarded state under the
owning lock, plus the two sanctioned exemptions — ``__init__`` (no
other thread holds a reference yet) and ``*_locked`` methods (the
caller-holds-the-lock convention)."""
import threading


class InProcHub:
    def __init__(self):
        self.lock = threading.RLock()
        self.beats = {}
        self.abort = None
        self.health = []

    def publish(self, rank, payload):
        with self.lock:
            self.beats[rank] = (1, dict(payload))

    def latch(self, payload):
        with self.lock:
            if self.abort is None:
                self.abort = dict(payload)

    def record(self, payload):
        with self.lock:
            self.health.append(dict(payload))
            self._trim_locked()

    def _trim_locked(self):
        # Caller holds self.lock (the *_locked naming convention).
        del self.health[:-4096]
