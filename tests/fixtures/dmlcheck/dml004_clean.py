# dmlcheck-virtual-path: distributed_machine_learning_tpu/train/loop.py
"""DML004 clean case: every host sync sits under a consumer guard, so
the no-consumer path stays a pointer test."""
import jax


def train_epoch(train_step, state, batches, events=None, metrics=None):
    for images, labels in batches:
        state, loss = train_step(state, images, labels)
        if events is not None:
            events.steps = int(jax.device_get(state.step))
        if metrics is not None:
            metrics.log(loss=float(loss))
    return state
