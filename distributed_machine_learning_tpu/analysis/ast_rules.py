"""Layer 1: stdlib-only AST lint rules over the repo source.

Each rule encodes one invariant this repo learned the hard way; the
docstring of every checker names the incident it descends from (the
rule table in docs/ARCHITECTURE.md cross-references them).  The module
imports NOTHING beyond the stdlib — ``tests/test_dmlcheck.py`` asserts
Layer 1 runs over the whole package in under 10 s without jax in
``sys.modules``.

Scope model: every rule declares which repo-relative paths it applies
to (``runtime/`` + ``telemetry/`` for the clock rules, ``tests/`` for
the marker rules, everywhere for the hygiene rules).  Fixtures under
``tests/fixtures/dmlcheck/`` carry a ``# dmlcheck-virtual-path:`` header
so a deliberate-violation snippet can exercise a scoped rule without
living at the scoped path — and that directory is excluded from real
scans for the same reason.
"""

from __future__ import annotations

import ast
import os
import re
import time
from typing import Callable, Iterable, Iterator

from distributed_machine_learning_tpu.analysis.findings import Finding

PACKAGE_DIR = "distributed_machine_learning_tpu"

# Directories a repo scan walks; fixtures are deliberate violations.
SCAN_DIRS = (PACKAGE_DIR, "tools", "tests")
EXCLUDE_PARTS = ("__pycache__", os.path.join("tests", "fixtures"))

VIRTUAL_PATH_RE = re.compile(r"#\s*dmlcheck-virtual-path:\s*(\S+)")


# ---------------------------------------------------------------------------
# Rule registry
# ---------------------------------------------------------------------------

class Rule:
    def __init__(self, rule_id: str, title: str, incident: str,
                 applies: Callable[[str], bool],
                 check: Callable[["FileContext"], Iterator[Finding]]):
        self.id = rule_id
        self.title = title
        self.incident = incident
        self.applies = applies
        self.check = check


RULES: dict[str, Rule] = {}


def _rule(rule_id: str, title: str, incident: str,
          applies: Callable[[str], bool]):
    def wrap(fn):
        RULES[rule_id] = Rule(rule_id, title, incident, applies, fn)
        return fn
    return wrap


class FileContext:
    """One parsed source file, with the shared lookups rules need."""

    def __init__(self, path: str, src: str):
        self.path = path.replace(os.sep, "/")
        self.src = src
        self.lines = src.splitlines()
        self.tree = ast.parse(src)
        self._parents: dict[ast.AST, ast.AST] | None = None

    def line(self, node: ast.AST) -> str:
        ln = getattr(node, "lineno", 0)
        return self.lines[ln - 1].strip() if 0 < ln <= len(self.lines) else ""

    def seg(self, node: ast.AST) -> str:
        return ast.get_source_segment(self.src, node) or self.line(node)

    def parents(self) -> dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {}
            for parent in ast.walk(self.tree):
                for child in ast.iter_child_nodes(parent):
                    self._parents[child] = parent
        return self._parents

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        parents = self.parents()
        while node in parents:
            node = parents[node]
            yield node

    def finding(self, rule_id: str, node: ast.AST, message: str,
                severity: str = "error") -> Finding:
        return Finding(rule=rule_id, file=self.path,
                       line=getattr(node, "lineno", 0), message=message,
                       snippet=self.line(node), severity=severity, layer=1)


# ---------------------------------------------------------------------------
# Shared AST predicates
# ---------------------------------------------------------------------------

def _call_name(node: ast.Call) -> str:
    """Dotted best-effort name of a call target ('os.path.getmtime')."""
    return _dotted(node.func)


def _dotted(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Call):
        return _dotted(node.func)
    return ""


_WALL_CALLS = {"time.time", "datetime.now", "datetime.utcnow",
               "datetime.datetime.now", "datetime.datetime.utcnow",
               "os.path.getmtime"}


def _is_wall_clock(node: ast.AST) -> bool:
    """A wall-clock reading: ``time.time()``, ``datetime.now()`` (and
    ``.timestamp()`` thereof), ``os.path.getmtime``, or an ``st_mtime``
    attribute.  ``st_mtime_ns`` used in EQUALITY is fine (change-
    signature staleness, the ISSUE 6 idiom) — callers only pass nodes
    that sit in ordering/subtraction positions."""
    if isinstance(node, ast.Call):
        name = _call_name(node)
        if name in _WALL_CALLS:
            return True
        # datetime.now().timestamp()
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "timestamp"
                and isinstance(node.func.value, ast.Call)
                and _is_wall_clock(node.func.value)):
            return True
        return False
    if isinstance(node, ast.Attribute):
        return node.attr in ("st_mtime", "st_mtime_ns")
    return False


def _contains_wall_clock(node: ast.AST, tainted: set[str]) -> bool:
    for sub in ast.walk(node):
        if _is_wall_clock(sub):
            return True
        if isinstance(sub, ast.Name) and sub.id in tainted:
            return True
    return False


def _functions(tree: ast.AST) -> Iterator[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _walk_scope(body: list, *, skip_functions: bool) -> Iterator[ast.AST]:
    """Walk statements/expressions under ``body``; with
    ``skip_functions`` nested function subtrees are not entered (their
    locals are a different scope)."""
    stack = list(reversed(body))
    while stack:
        node = stack.pop()
        if skip_functions and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(reversed(list(ast.iter_child_nodes(node))))


def _ordered_walk(node: ast.AST) -> list[ast.AST]:
    """Every descendant sorted by source position — ``ast.walk`` is
    BFS, which breaks anything order-sensitive (taint tracking)."""
    return sorted(
        (n for n in ast.walk(node) if hasattr(n, "lineno")),
        key=lambda n: (n.lineno, n.col_offset),
    )


def _assigned_names(target: ast.AST) -> Iterator[str]:
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _assigned_names(elt)


def _in_package(path: str) -> bool:
    return path.startswith(PACKAGE_DIR + "/")


def _pkg_or_tools(path: str) -> bool:
    return _in_package(path) or path.startswith("tools/")


def _everywhere(path: str) -> bool:
    return True


def _tests_only(path: str) -> bool:
    return path.startswith("tests/")


# ---------------------------------------------------------------------------
# DML001 — wall-clock arithmetic (the ISSUE 6 monotonic-clock ban)
# ---------------------------------------------------------------------------

@_rule(
    "DML001", "wall-clock reading used in ordering or subtraction",
    "ISSUE 6: cross-host mtime/wall-clock staleness misjudged peers by "
    "routine NFS clock skew; the heartbeat sampler was rebuilt on "
    "change-signatures + the local monotonic clock.",
    _pkg_or_tools,
)
def check_wall_clock_arithmetic(ctx: FileContext) -> Iterator[Finding]:
    """``time.time()`` / ``datetime.now()`` / ``getmtime`` / ``st_mtime``
    in a ``<``/``>`` comparison or a subtraction — durations and
    staleness must use ``time.monotonic()``/``perf_counter`` (equality
    on ``st_mtime_ns`` is the sanctioned change-signature idiom and is
    NOT flagged).  Recording a wall timestamp into a payload is fine;
    doing arithmetic on one is the bug."""
    # Each scope (module body, each function body) is taint-tracked
    # independently; nested functions are their own scope, so `now`
    # meaning monotonic in one function never poisons another.
    scopes: list[list] = [ctx.tree.body]
    scopes += [fn.body for fn in _functions(ctx.tree)]
    for body in scopes:
        tainted: set[str] = set()
        for node in _walk_scope(body, skip_functions=True):
            if isinstance(node, ast.Assign) and _is_wall_clock(node.value):
                for t in node.targets:
                    tainted.update(_assigned_names(t))
        for node in _walk_scope(body, skip_functions=True):
            bad = None
            if isinstance(node, ast.Compare) and any(
                    isinstance(op, (ast.Lt, ast.LtE, ast.Gt, ast.GtE))
                    for op in node.ops):
                operands = [node.left, *node.comparators]
                if any(_contains_wall_clock(o, tainted) for o in operands):
                    bad = node
            elif isinstance(node, ast.BinOp) and isinstance(
                    node.op, ast.Sub):
                if (_contains_wall_clock(node.left, tainted)
                        or _contains_wall_clock(node.right, tainted)):
                    bad = node
            if bad is not None:
                yield ctx.finding(
                    "DML001", bad,
                    "wall-clock reading used in ordering/subtraction "
                    "— cross-host wall clocks and file mtimes skew "
                    "by minutes on shared mounts; use "
                    "time.monotonic()/perf_counter for durations "
                    "and change-signatures for staleness",
                )


# ---------------------------------------------------------------------------
# DML002 — ledger writes must flush+fsync (ISSUE 3 fired-fault ledger)
# ---------------------------------------------------------------------------

# Token must not be the tail of a longer word ('default' is not
# 'fault'); a leading '_'/'.'/quote is how the tokens appear in real
# identifiers (self._ledger_path, gang_health.jsonl, consumed_rank).
_LEDGER_TOKEN_RE = re.compile(
    r"(?<![a-z])(ledger|fault|health|consumed)", re.IGNORECASE)


def _ledgerish(path_src: str) -> bool:
    return _LEDGER_TOKEN_RE.search(path_src) is not None


@_rule(
    "DML002", "ledger append without flush+fsync",
    "ISSUE 3: the fired-fault ledger is read by the relaunched gang — a "
    "buffered entry lost to the very next os._exit re-fires the fault "
    "every attempt and no restart budget suffices.",
    _pkg_or_tools,
)
def check_ledger_fsync(ctx: FileContext) -> Iterator[Finding]:
    """Every ``with open(<ledger-ish path>, "a")`` block must call both
    ``.flush()`` and ``os.fsync(...)`` before leaving — the writer's
    very next statement may be ``os._exit`` (coordinated abort, injected
    kill), which skips buffered IO.  Ledger-ish = the path expression
    mentions ledger/fault/health/consumed (``faults_fired.jsonl``,
    ``gang_health.jsonl``, ``consumed_rank<r>.jsonl``, ``*_ledger``)."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.With):
            continue
        for item in node.items:
            call = item.context_expr
            if not (isinstance(call, ast.Call)
                    and _call_name(call) == "open" and len(call.args) >= 2):
                continue
            mode = call.args[1]
            if not (isinstance(mode, ast.Constant)
                    and isinstance(mode.value, str)
                    and mode.value.startswith("a")):
                continue
            path_src = ctx.seg(call.args[0])
            if not _ledgerish(path_src):
                continue
            body_src = "\n".join(ctx.seg(s) for s in node.body)
            has_flush = ".flush()" in body_src
            has_fsync = "fsync(" in body_src
            if not (has_flush and has_fsync):
                missing = [w for w, ok in (("flush", has_flush),
                                           ("os.fsync", has_fsync))
                           if not ok]
                yield ctx.finding(
                    "DML002", node,
                    f"ledger append without {' + '.join(missing)} — the "
                    "next statement may be os._exit (abort/kill), which "
                    "drops buffered rows; the relaunch then replays "
                    "history that was never durable",
                )


# ---------------------------------------------------------------------------
# DML003 — restored buffers into a donating step (ISSUE 1 segfault)
# ---------------------------------------------------------------------------

# Raw restore surfaces whose results alias storage (orbax/tensorstore
# zero-copy).  restore_checkpoint / reshard_restore are NOT here: they
# re-materialize through fresh_buffers internally (the ISSUE 1 fix) and
# are the safe front doors.
_RESTORE_CALLS = ()
_RESTORE_ATTRS = ("restore",)           # orbax ckptr.restore(...)
_RESTORE_NAME_RE = re.compile(r"(^|_)raw_restore|restore_raw")
_CLEANSE_CALLS = ("fresh_buffers", "_fresh_buffers")


@_rule(
    "DML003", "restored/aliased buffers handed to a donating step",
    "ISSUE 1: zero-copy numpy/tensorstore aliases of restored leaves "
    "fed to a donate_argnums step segfaulted the seed suite — donation "
    "frees the buffer under the alias (fixed with checkpoint.py::"
    "fresh_buffers).",
    _in_package,
)
def check_restore_then_donate(ctx: FileContext) -> Iterator[Finding]:
    """Intra-function taint: a name bound from a raw restore (orbax
    ``.restore(...)``, ``reshard_restore``) must pass through
    ``fresh_buffers`` before being handed to any ``*step*`` call — the
    compiled steps donate their state argument, and a restore's zero-
    copy aliases die with the donated buffer.  (``restore_checkpoint``
    re-materializes internally and is safe to call directly.)"""
    for fn in _functions(ctx.tree):
        tainted: set[str] = set()
        # Source-position order approximates execution order well
        # enough for a lint (ast.walk is BFS, which does not).
        for stmt in _ordered_walk(fn):
            if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, ast.Call):
                call = stmt.value
                name = _call_name(call)
                attr = (call.func.attr
                        if isinstance(call.func, ast.Attribute) else "")
                targets = [n for t in stmt.targets
                           for n in _assigned_names(t)]
                if (name.split(".")[-1] in _RESTORE_CALLS
                        or attr in _RESTORE_ATTRS
                        or _RESTORE_NAME_RE.search(name.split(".")[-1])):
                    tainted.update(targets)
                elif name.split(".")[-1] in _CLEANSE_CALLS:
                    tainted.difference_update(targets)
            if isinstance(stmt, ast.Call):
                callee = _call_name(stmt).split(".")[-1]
                if "step" in callee and callee not in _CLEANSE_CALLS:
                    for arg in stmt.args:
                        if (isinstance(arg, ast.Name)
                                and arg.id in tainted):
                            yield ctx.finding(
                                "DML003", stmt,
                                f"{arg.id!r} holds a raw restore result "
                                f"and is passed to {callee!r} — the step "
                                "donates its state, freeing the restored "
                                "buffers under their zero-copy aliases; "
                                "re-materialize via train.checkpoint."
                                "fresh_buffers first",
                            )


# ---------------------------------------------------------------------------
# DML004 — host syncs in the hot training loop (ISSUE 2 +2.8% budget)
# ---------------------------------------------------------------------------

_GUARD_TOKENS = ("tel", "telemetry", "events", "metrics", "until_step",
                 "watchdog", "stop", "loss_print_every", "warmup",
                 "profil")


@_rule(
    "DML004", "unguarded host sync in the train-loop hot path",
    "ISSUE 2 set the telemetry-off budget at ONE pointer test per step; "
    "every device_get/block_until_ready serializes dispatch and an "
    "unguarded one taxes every run, consumers or not.",
    lambda p: p.endswith("train/loop.py"),
)
def check_hot_loop_host_sync(ctx: FileContext) -> Iterator[Finding]:
    """Inside the per-step loops of ``train/loop.py``'s ``train*``
    functions, ``jax.device_get`` / ``.block_until_ready`` / ``.item()``
    / ``float(loss-or-state)`` must sit under a consumer guard (``if
    events is not None:``, ``if tel is not None:``, the print-interval
    test, ...) so the no-consumer path stays a pointer test.  The one
    deliberate exception — the reference measurement protocol's
    ``block_until_ready`` timing bracket — is a baselined suppression,
    not a pass."""
    for fn in _functions(ctx.tree):
        if "train" not in fn.name:
            continue
        for loop in ast.walk(fn):
            if not isinstance(loop, (ast.While, ast.For)):
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call):
                    continue
                name = _call_name(node)
                sync = (
                    name in ("jax.device_get", "jax.block_until_ready")
                    or (isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("block_until_ready",
                                               "item"))
                    or (name == "float" and node.args and any(
                        isinstance(s, ast.Name)
                        and ("loss" in s.id or "state" in s.id)
                        for s in ast.walk(node.args[0])))
                )
                if not sync:
                    continue
                guarded = False
                for anc in ctx.ancestors(node):
                    test = getattr(anc, "test", None)
                    if isinstance(anc, (ast.If, ast.IfExp)) and \
                            test is not None:
                        test_src = ctx.seg(test)
                        if any(tok in test_src for tok in _GUARD_TOKENS):
                            guarded = True
                            break
                    if anc is loop:
                        break
                if not guarded:
                    yield ctx.finding(
                        "DML004", node,
                        f"{name or 'host sync'} in the hot loop outside "
                        "any consumer guard — serializes dispatch on "
                        "every step even when nothing reads the value",
                    )


# ---------------------------------------------------------------------------
# DML005 — bare/swallowing exception handlers (ISSUE 3 verify chain)
# ---------------------------------------------------------------------------

@_rule(
    "DML005", "bare except / swallowed verification error",
    "ISSUE 3: a swallowed CheckpointVerifyError turns a detected-corrupt "
    "checkpoint into silent garbage params — the fallback chain exists "
    "so the error has somewhere to go.",
    _pkg_or_tools,
)
def check_swallowed_errors(ctx: FileContext) -> Iterator[Finding]:
    """Flags ``except:`` (catches SystemExit/KeyboardInterrupt — breaks
    the gang teardown paths) and ``except CheckpointVerifyError/
    Exception: pass`` bodies that neither re-raise, log, count, nor
    inspect the exception — a verification error with no consumer is
    corruption waved through."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            yield ctx.finding(
                "DML005", node,
                "bare 'except:' catches SystemExit/KeyboardInterrupt too "
                "— it swallows the gang teardown/drain paths; name the "
                "exceptions you mean",
            )
            continue
        caught = ctx.seg(node.type)
        if not ("CheckpointVerifyError" in caught
                or caught.strip() == "Exception"):
            continue
        body_is_noop = all(
            isinstance(s, (ast.Pass, ast.Continue)) for s in node.body)
        if body_is_noop:
            yield ctx.finding(
                "DML005", node,
                f"'except {caught.strip()}' swallowed with no re-raise, "
                "log, or counter — a detected failure must reach a "
                "consumer (fallback chain, FaultEvents, at least a log)",
            )


# ---------------------------------------------------------------------------
# DML006 — heavy tests must be marked (ISSUE 6 marker guard, extended)
# ---------------------------------------------------------------------------

_SPAWN_TOKENS = ("cli.gang", "runtime.gang_worker", "gang_worker.py",
                 "mh_worker")
_MESH_BUILDERS = ("make_mesh", "Mesh")


def _string_constants(node: ast.AST) -> Iterator[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub.value


def _spawns_gang(node: ast.AST) -> bool:
    return any(any(tok in s for tok in _SPAWN_TOKENS)
               for s in _string_constants(node))


def _big_mesh(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if (isinstance(sub, ast.Call)
                and _call_name(sub).split(".")[-1] in _MESH_BUILDERS
                and sub.args
                and isinstance(sub.args[0], ast.Constant)
                and isinstance(sub.args[0].value, int)
                and sub.args[0].value > 8):
            return True
    for s in _string_constants(node):
        m = re.search(r"host_platform_device_count=(\d+)", s)
        if m and int(m.group(1)) > 8:
            return True
    return False


@_rule(
    "DML006", "gang/large-mesh test without slow|faultinject marker",
    "ISSUE 6's conftest guard bans unregistered markers; tier-1 runs "
    "~500-750s against an 870s timeout, so a multi-process gang test "
    "slipping into the default run is a suite timeout, not a slowdown.",
    _tests_only,
)
def check_heavy_test_markers(ctx: FileContext) -> Iterator[Finding]:
    """A test that spawns worker processes (``cli.gang`` /
    ``gang_worker`` / ``mh_worker`` module paths, directly or via a
    module-level helper) or builds a >8-device mesh must carry
    ``@pytest.mark.slow`` or ``@pytest.mark.faultinject`` — resource
    classes, extending the marker-registration guard in conftest."""
    spawner_helpers = {
        fn.name for fn in _functions(ctx.tree)
        if not fn.name.startswith("test_")
        and (_spawns_gang(fn) or _big_mesh(fn))
    }
    for fn in _functions(ctx.tree):
        if not fn.name.startswith("test_"):
            continue
        marked = any(
            tok in ctx.seg(d)
            for d in fn.decorator_list
            for tok in ("slow", "faultinject")
        )
        if marked:
            continue
        calls_spawner = any(
            isinstance(n, ast.Call)
            and _call_name(n).split(".")[-1] in spawner_helpers
            for n in ast.walk(fn))
        if _spawns_gang(fn) or _big_mesh(fn) or calls_spawner:
            yield ctx.finding(
                "DML006", fn,
                f"{fn.name} spawns gang workers / a >8-device mesh but "
                "carries neither @pytest.mark.slow nor .faultinject — "
                "tier-1's timeout headroom cannot absorb it",
            )


# ---------------------------------------------------------------------------
# DML007 — mutable defaults + nondeterministic manifest payloads
# ---------------------------------------------------------------------------

@_rule(
    "DML007", "mutable default arg / nondeterministic manifest payload",
    "ISSUE 5: checkpoint manifests are compared digest-for-digest "
    "across ranks and world sizes — any nondeterminism in the payload "
    "(wall timestamps, shared mutable defaults) breaks the bit-"
    "identical resharding proof.",
    _pkg_or_tools,
)
def check_deterministic_payloads(ctx: FileContext) -> Iterator[Finding]:
    """(a) Mutable default arguments anywhere (a shared list/dict
    default leaks state across calls — in ledger/manifest builders that
    is cross-rank divergence); (b) wall-clock / datetime readings inside
    ``train/checkpoint.py``'s manifest-building functions, whose output
    every rank must reproduce byte-for-byte."""
    for fn in _functions(ctx.tree):
        for default in [*fn.args.defaults, *fn.args.kw_defaults]:
            if default is None:
                continue
            mutable = isinstance(default, (ast.List, ast.Dict, ast.Set))
            if (isinstance(default, ast.Call)
                    and _call_name(default) in ("list", "dict", "set")):
                mutable = True
            if mutable:
                yield ctx.finding(
                    "DML007", default,
                    f"mutable default argument in {fn.name}() — shared "
                    "across calls; use None + in-body construction",
                )
    if ctx.path.endswith("train/checkpoint.py") or \
            "manifest" in os.path.basename(ctx.path):
        for fn in _functions(ctx.tree):
            if not ("manifest" in fn.name or "save_checkpoint" in fn.name):
                continue
            for node in ast.walk(fn):
                if isinstance(node, (ast.Call, ast.Attribute)) and \
                        _is_wall_clock(node):
                    yield ctx.finding(
                        "DML007", node,
                        f"wall-clock reading inside {fn.name}() — "
                        "manifest payloads are digest-compared across "
                        "ranks and must be deterministic",
                    )
                    break


# ---------------------------------------------------------------------------
# DML008 — subprocess without timeout in tests/tools
# ---------------------------------------------------------------------------

@_rule(
    "DML008", "subprocess call without timeout",
    "Tier-1 runs against a hard 870s kill: one hung child (wedged "
    "rendezvous, dead gang) eats the entire suite budget instead of "
    "failing one test.",
    lambda p: p.startswith("tests/") or p.startswith("tools/"),
)
def check_subprocess_timeout(ctx: FileContext) -> Iterator[Finding]:
    """``subprocess.run``/``check_output``/``check_call`` must pass
    ``timeout=`` — a child that never exits must fail its own test, not
    outlive the suite.  (``Popen`` is exempt: its bound lives on the
    later ``communicate(timeout=...)``.)"""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name not in ("subprocess.run", "subprocess.check_output",
                        "subprocess.check_call"):
            continue
        if not any(k.arg == "timeout" for k in node.keywords):
            yield ctx.finding(
                "DML008", node,
                f"{name}(...) without timeout= — a hung child consumes "
                "the tier-1 suite's whole 870s budget",
            )


# ---------------------------------------------------------------------------
# DML009 — SystemExit/BaseException swallowed (ISSUE 6 drain path)
# ---------------------------------------------------------------------------

@_rule(
    "DML009", "SystemExit/BaseException caught without propagating",
    "ISSUE 6: gang_worker converts SIGTERM → SystemExit → flush-then-"
    "die; a handler that eats SystemExit turns a coordinated drain "
    "into a zombie rank whose telemetry never reaches disk.",
    _everywhere,
)
def check_base_exception_swallow(ctx: FileContext) -> Iterator[Finding]:
    """A handler catching ``SystemExit`` or ``BaseException`` must
    either re-``raise`` or visibly hand the exception off (reference
    the bound name — the loader's producer-thread channel pattern).
    ``KeyboardInterrupt`` alone is exempt (deliberate ctrl-C handling
    in the watch tools)."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler) or node.type is None:
            continue
        caught = ctx.seg(node.type)
        if not ("SystemExit" in caught or "BaseException" in caught):
            continue
        has_raise = any(isinstance(s, ast.Raise)
                        for s in ast.walk(node))
        uses_exc = node.name is not None and any(
            isinstance(s, ast.Name) and s.id == node.name
            for b in node.body for s in ast.walk(b))
        if not (has_raise or uses_exc):
            yield ctx.finding(
                "DML009", node,
                f"'except {caught.strip()}' neither re-raises nor hands "
                "the exception off — this eats the SIGTERM→SystemExit "
                "drain path (flush-then-die) and process teardown",
            )


# ---------------------------------------------------------------------------
# DML010 — append-only artifacts opened in truncate mode
# ---------------------------------------------------------------------------

@_rule(
    "DML010", "append-only ledger/stream opened with mode 'w'",
    "ISSUE 2: a supervisor re-exec resumes attempt numbering from disk "
    "so restarts APPEND, never truncate — 'w' on a JSONL stream erases "
    "the pre-crash attempts a post-mortem needs.",
    _pkg_or_tools,
)
def check_ledger_truncate(ctx: FileContext) -> Iterator[Finding]:
    """``open(<*.jsonl or ledger-ish path>, "w")`` — the JSONL streams
    (metrics, ledgers, health events, consumption records) are whole-
    run history; writers must append."""
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and _call_name(node) == "open" and len(node.args) >= 2):
            continue
        mode = node.args[1]
        if not (isinstance(mode, ast.Constant)
                and isinstance(mode.value, str)
                and mode.value.startswith("w")):
            continue
        path_src = ctx.seg(node.args[0])
        if ".jsonl" in path_src.lower() or _ledgerish(path_src):
            yield ctx.finding(
                "DML010", node,
                "append-only JSONL/ledger opened with mode "
                f"{mode.value!r} — truncates whole-run history that "
                "restarts and post-mortems read; open with 'a'",
            )


# ---------------------------------------------------------------------------
# DML011 — os._exit outside the runtime package
# ---------------------------------------------------------------------------

@_rule(
    "DML011", "os._exit outside runtime/",
    "ISSUE 3: os._exit skips atexit, buffered IO, and telemetry flush "
    "— only the coordinated-abort/fault paths (which flush explicitly "
    "first) may hard-exit, and they live in runtime/.",
    lambda p: _in_package(p) and "/runtime/" not in p,
)
def check_hard_exit_scope(ctx: FileContext) -> Iterator[Finding]:
    """``os._exit`` anywhere in the package outside ``runtime/`` — the
    sanctioned hard-exit sites (coordinator abort, fault injection,
    watchdog escalation) all flush their ledgers/telemetry first and
    are deliberately confined to the runtime package."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and _call_name(node) == "os._exit":
            yield ctx.finding(
                "DML011", node,
                "os._exit outside runtime/ — skips buffered IO and "
                "telemetry flush; route through the runtime abort paths "
                "(which flush first) or raise SystemExit",
            )


# ---------------------------------------------------------------------------
# DML012 — socket/HTTP IO without an explicit timeout (ISSUE 12)
# ---------------------------------------------------------------------------

_TIMEOUT_TOKENS = ("settimeout(", "setdefaulttimeout(")


def _runtime_scope(path: str) -> bool:
    return f"{PACKAGE_DIR}/runtime/" in path or path.startswith("tools/")


@_rule(
    "DML012", "socket/HTTP call without an explicit timeout",
    "ISSUE 12: the TCP gang transport is the control plane a BLOCKED "
    "rank escapes through — a monitor thread hung in an unbounded "
    "connect/recv can neither detect peers nor join an abort, turning "
    "one lost packet into a wedged gang.",
    _runtime_scope,
)
def check_socket_timeouts(ctx: FileContext) -> Iterator[Finding]:
    """Under ``runtime/`` and ``tools/``: (a)
    ``socket.create_connection`` needs its timeout argument (second
    positional or ``timeout=``); (b) ``urlopen`` and
    ``http.client.HTTP(S)Connection`` need ``timeout=``; (c) a
    function that constructs a raw ``socket.socket`` must call
    ``settimeout`` (or ``socket.setdefaulttimeout``) somewhere in its
    body — every blocking socket op in the gang control plane must be
    bounded."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        tail = name.split(".")[-1]
        has_timeout_kw = any(k.arg == "timeout" for k in node.keywords)
        if tail == "create_connection" and not (
                len(node.args) >= 2 or has_timeout_kw):
            yield ctx.finding(
                "DML012", node,
                "socket.create_connection without a timeout — an "
                "unreachable gang server must fail the op (retry/"
                "backoff path), not hang the monitor thread",
            )
        elif tail == "urlopen" and not has_timeout_kw:
            yield ctx.finding(
                "DML012", node,
                "urlopen without timeout= — unbounded HTTP IO in the "
                "runtime/tools layer",
            )
        elif tail in ("HTTPConnection", "HTTPSConnection") \
                and not has_timeout_kw:
            yield ctx.finding(
                "DML012", node,
                f"{tail} without timeout= — unbounded HTTP IO in the "
                "runtime/tools layer",
            )
    for fn in _functions(ctx.tree):
        # Find a raw-socket construction FIRST: reconstructing body
        # source (get_source_segment is O(file) per statement) for
        # every socket-free function made this rule 6s of the <10s
        # layer-1 budget.
        sock_node = None
        for node in _walk_scope(fn.body, skip_functions=True):
            if (isinstance(node, ast.Call)
                    and _call_name(node) == "socket.socket"):
                sock_node = node
                break
        if sock_node is None:
            continue
        body_src = "\n".join(ctx.seg(s) for s in fn.body)
        if any(tok in body_src for tok in _TIMEOUT_TOKENS):
            continue
        yield ctx.finding(
            "DML012", sock_node,
            f"{fn.name}() constructs a raw socket but never "
            "calls settimeout — every blocking socket op in "
            "the gang control plane must be bounded",
        )


# ---------------------------------------------------------------------------
# DML013 / DML014 — lock discipline on the gang control plane (ISSUE 15)
# ---------------------------------------------------------------------------

# Per-class lock-ownership map for the shared control-plane state: which
# attributes are guarded, and which context-manager names count as
# holding their lock when they appear in a `with`.  `_locked` is
# InProcTransport's lock+epoch-fence contextmanager; methods whose NAME
# ends in `_locked` are the documented caller-holds-the-lock convention
# and are exempt (their callers are checked instead).  GangCoordinator
# is deliberately absent: its counters are single-writer with
# GIL-atomic cross-thread reads, not lock-owned shared state.
_LOCK_OWNERSHIP = {
    "InProcHub": {
        "attrs": {"beats", "abort", "joins", "restore", "health",
                  "faults", "consumed", "box", "epoch", "_version",
                  "serving_requests", "serving_results",
                  "serving_drain", "serving_epoch", "serving_role"},
        "locks": {"lock", "_locked"},
    },
    "InProcTransport": {
        "attrs": {"beats", "abort", "joins", "restore", "health",
                  "faults", "consumed", "box", "epoch", "_version",
                  "serving_requests", "serving_results",
                  "serving_drain", "serving_epoch", "serving_role"},
        "locks": {"lock", "_locked"},
    },
    "TcpGangServer": {
        "attrs": {"_seen"},
        "locks": {"_seen_lock", "lock", "_locked"},
    },
}

_MUTATOR_METHODS = {"append", "pop", "clear", "setdefault", "popitem",
                    "update", "extend", "add", "remove", "insert",
                    "discard"}


def _guarded_attr_of(node: ast.AST, attrs: set[str]) -> str | None:
    """The guarded attribute a MUTATION node touches, else None.
    Covers: `x.attr = v` / `x.attr += v`, `x.attr[k] = v`,
    `del x.attr[k]`, and `x.attr.append(...)`-style mutator calls
    (including through a `.setdefault(...)` chain)."""
    def attr_of(value: ast.AST) -> str | None:
        if isinstance(value, ast.Attribute) and value.attr in attrs:
            return value.attr
        return None

    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            hit = attr_of(t)
            if hit:
                return hit
            if isinstance(t, ast.Subscript):
                hit = attr_of(t.value)
                if hit:
                    return hit
    if isinstance(node, ast.Delete):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                hit = attr_of(t.value)
                if hit:
                    return hit
    if (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATOR_METHODS):
        base = node.func.value
        hit = attr_of(base)
        if hit:
            return hit
        # hub.consumed.setdefault(r, []).append(...) — the mutator
        # hangs off another call whose receiver is the guarded attr.
        if isinstance(base, ast.Call) and isinstance(
                base.func, ast.Attribute):
            return attr_of(base.func.value)
    return None


def _tested_attr_of(ctx: FileContext, node: ast.AST,
                    attrs: set[str]) -> str | None:
    """The guarded attribute a CHECK node reads for a decision, else
    None: `k in x.attr` / `not in`, `x.attr.get(...)`, and
    `x.attr is (not) None`."""
    def attr_of(value: ast.AST) -> str | None:
        if isinstance(value, ast.Attribute) and value.attr in attrs:
            return value.attr
        return None

    if isinstance(node, ast.Compare) and len(node.ops) == 1:
        if isinstance(node.ops[0], (ast.In, ast.NotIn)):
            return attr_of(node.comparators[0])
        if (isinstance(node.ops[0], (ast.Is, ast.IsNot))
                and isinstance(node.comparators[0], ast.Constant)
                and node.comparators[0].value is None):
            return attr_of(node.left)
    if (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"):
        return attr_of(node.func.value)
    return None


def _innermost_lock_with(ctx: FileContext, node: ast.AST,
                         lock_tokens: set[str]):
    """The nearest enclosing `with` whose context expression's trailing
    name is one of ``lock_tokens`` (e.g. ``self.lock``, ``hub.lock``,
    ``self._locked("…")``, ``self._seen_lock``) — None when the node
    runs lockless.  Stops at the enclosing function boundary: a nested
    function's body does not inherit its definer's lock."""
    for anc in ctx.ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                tail = _dotted(item.context_expr).split(".")[-1]
                if tail in lock_tokens:
                    return anc
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None
    return None


def _mapped_class_methods(ctx: FileContext):
    """(class spec, method) pairs for classes in the ownership map,
    minus the exempt methods (`__init__` builds state before any
    other thread can hold a reference; `*_locked` methods document
    caller-holds-the-lock)."""
    for cls in ast.walk(ctx.tree):
        if (not isinstance(cls, ast.ClassDef)
                or cls.name not in _LOCK_OWNERSHIP):
            continue
        spec = _LOCK_OWNERSHIP[cls.name]
        for stmt in cls.body:
            if not isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if stmt.name == "__init__" or stmt.name.endswith("_locked"):
                continue
            yield cls.name, spec, stmt


@_rule(
    "DML013", "shared control-plane state written outside its lock",
    "ISSUE 15: every correctness claim of the gang transport "
    "(exactly-once appends, first-writer-wins abort, epoch fencing) is "
    "a property of mutations happening under the owning lock — one "
    "unlocked write is a data race the interleaving explorer can only "
    "find after the fact.",
    _runtime_scope,
)
def check_unlocked_shared_writes(ctx: FileContext) -> Iterator[Finding]:
    """Writes to the lock-owned attributes of ``InProcHub`` /
    ``InProcTransport`` / ``TcpGangServer`` (per the per-class
    ownership map) that are not lexically inside a ``with`` holding the
    owning lock.  Direct assignment, subscript stores, ``del``, and
    mutating method calls (``append``/``pop``/``clear``/…) all count."""
    for cls_name, spec, fn in _mapped_class_methods(ctx):
        for node in ast.walk(fn):
            attr = _guarded_attr_of(node, spec["attrs"])
            if attr is None:
                continue
            if _innermost_lock_with(ctx, node, spec["locks"]) is None:
                yield ctx.finding(
                    "DML013", node,
                    f"{cls_name}.{fn.name} mutates shared attribute "
                    f"{attr!r} outside its owning lock "
                    f"({'/'.join(sorted(spec['locks']))}) — a data "
                    "race on the gang control plane; hold the lock or "
                    "rename the method *_locked and take it in every "
                    "caller",
                )


@_rule(
    "DML014", "check-then-act on shared state across lock scopes",
    "ISSUE 15: PR 12's dedup store relied on a membership check and "
    "the reservation insert being ONE critical section — split across "
    "lock scopes, a duplicate op passes the check before the original "
    "inserts and the append double-fires (the exact bug the layer-3 "
    "dedup_inflight scenario replays).",
    _runtime_scope,
)
def check_check_then_act(ctx: FileContext) -> Iterator[Finding]:
    """A decision read of a guarded attribute (membership test,
    ``.get``, ``is None``) whose own lock scope contains NO mutation of
    that attribute, while the same function mutates it in a DIFFERENT
    lock scope (or the test runs lockless) — the check and the act can
    interleave with another thread's act.  The sanctioned idiom —
    test + reservation write in one ``with`` block — does not fire."""
    for cls_name, spec, fn in _mapped_class_methods(ctx):
        mutations = []
        for node in ast.walk(fn):
            attr = _guarded_attr_of(node, spec["attrs"])
            if attr is not None:
                mutations.append((attr, node))
        if not mutations:
            continue
        for node in ast.walk(fn):
            attr = _tested_attr_of(ctx, node, spec["attrs"])
            if attr is None:
                continue
            if not any(a == attr for a, _ in mutations):
                continue
            w = _innermost_lock_with(ctx, node, spec["locks"])
            if w is not None and any(
                    a == attr and m in set(ast.walk(w))
                    for a, m in mutations):
                continue   # check and act share one critical section
            yield ctx.finding(
                "DML014", node,
                f"{cls_name}.{fn.name} checks {attr!r} "
                + ("outside any lock" if w is None
                   else "in one lock scope")
                + " but mutates it in another — check-then-act race; "
                "fold the test and the mutation into one critical "
                "section",
            )


# ---------------------------------------------------------------------------
# DML015 — serving spans / stage journeys must close on every exit (ISSUE 17)
# ---------------------------------------------------------------------------

# Stage names that OPEN work on a request (a replica has taken
# ownership and the stage histograms now expect a terminal stamp) vs
# the stamps that END a journey leg.  ``taken`` is deliberately NOT an
# open stage: the ``take_requests`` wrapper stamps it and returns —
# ownership of the close belongs to the worker loop consuming the
# batch, which the rule checks separately.
_OPEN_STAGE_NAMES = frozenset({"bound", "computed"})
_TERMINAL_STAGE_NAMES = frozenset(
    {"posted", "completed", "requeued", "fenced", "dropped"})


def _stage_of_stamp(node: ast.Call) -> str | None:
    """Literal stage name of a ``stamp_stage(payload, "stage", …)``
    call, else None (dynamic stage names are invisible to this rule)."""
    if _call_name(node).split(".")[-1] != "stamp_stage":
        return None
    if (len(node.args) >= 2 and isinstance(node.args[1], ast.Constant)
            and isinstance(node.args[1].value, str)):
        return node.args[1].value
    return None


def _with_item_names(scope_body: list) -> set[str]:
    """Names appearing inside a ``with``-item context expression in the
    scope — ``span = tel.span(…) if tel else nullcontext()`` followed by
    ``with span:`` is the sanctioned conditional-span idiom."""
    names: set[str] = set()
    for node in _walk_scope(scope_body, skip_functions=True):
        if isinstance(node, ast.With):
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Name):
                        names.add(sub.id)
    return names


@_rule(
    "DML015",
    "serving span or stage journey opened without a close on every exit",
    "ISSUE 17: per-stage histograms and merged Perfetto timelines are "
    "only trustworthy when every span object reaches __exit__ and every "
    "open-stage stamp (bound/computed) has a terminal stamp "
    "(posted/completed/requeued/fenced/dropped) reachable in the same "
    "function — an abandoned span or journey silently skews stage "
    "latencies and hides the very stall the trace exists to show.",
    _pkg_or_tools,
)
def check_unclosed_serving_spans(ctx: FileContext) -> Iterator[Finding]:
    """Two shapes of abandoned observability state:

    A. a ``…span(…)`` tracer call that is not context-managed — not a
       ``with`` item, not returned (the ``Telemetry.span`` forwarding
       idiom: the caller manages it), not handed to ``enter_context``,
       and not assigned to a name later used as a ``with`` item in the
       same scope.  Any exception then skips ``__exit__`` and the trace
       keeps a torn span;
    B. a function that stamps an OPEN stage (``bound``/``computed``)
       but contains NO terminal stamp anywhere — no exit path of that
       function can ever close the journey it opened, so a fence,
       requeue, or crash leaves the record dangling.
    """
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if not (name == "span" or name.endswith(".span")):
            continue
        managed = False
        assigned: set[str] = set()
        scope_body = ctx.tree.body
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.withitem, ast.Return)):
                managed = True
                break
            if (isinstance(anc, ast.Call)
                    and _call_name(anc).split(".")[-1]
                    == "enter_context"):
                managed = True
                break
            if isinstance(anc, (ast.Assign, ast.AnnAssign,
                                ast.NamedExpr)):
                targets = (anc.targets if isinstance(anc, ast.Assign)
                           else [anc.target])
                for t in targets:
                    assigned.update(_assigned_names(t))
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope_body = anc.body
                break
        if managed:
            continue
        if assigned and assigned & _with_item_names(scope_body):
            continue
        yield ctx.finding(
            "DML015", node,
            f"span object from {name!r} is never context-managed — "
            "not a `with` item, not returned, not enter_context-ed, "
            "not assigned to a name a later `with` uses; an exception "
            "skips its __exit__ and the trace keeps a torn span",
        )
    for fn in _functions(ctx.tree):
        opens: list[tuple[str, ast.Call]] = []
        closes = False
        for node in _walk_scope(fn.body, skip_functions=True):
            if not isinstance(node, ast.Call):
                continue
            stage = _stage_of_stamp(node)
            if stage in _OPEN_STAGE_NAMES:
                opens.append((stage, node))
            elif stage in _TERMINAL_STAGE_NAMES:
                closes = True
        if opens and not closes:
            stage, node = opens[0]
            yield ctx.finding(
                "DML015", node,
                f"{fn.name} stamps open stage {stage!r} but contains "
                "no terminal stamp (posted/completed/requeued/fenced/"
                "dropped) — no exit path of this function can close "
                "the journey it opened",
            )


# ---------------------------------------------------------------------------
# DML016 — the digital twin must never touch a real clock (ISSUE 20)
# ---------------------------------------------------------------------------

# Real-clock readings AND real sleeps: the virtual-clock modules price
# time arithmetically and advance a VirtualClock; any of these leaking
# in couples the modeled trajectory to host scheduling.
_REAL_CLOCK_CALLS = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.process_time",
    "time.clock_gettime", "time.sleep",
    "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow",
})


def _virtual_clock_scope(path: str) -> bool:
    """The modeled-network modules: ``runtime/netmodel.py`` and any
    sibling whose filename says it belongs to the twin (fixtures map
    here via their virtual-path header)."""
    return _in_package(path) and "netmodel" in path.rsplit("/", 1)[-1]


@_rule(
    "DML016",
    "real clock or sleep inside a virtual-clock (digital twin) path",
    "ISSUE 20: the pod-scale twin replays 512-rank gray-failure "
    "campaigns deterministically because every duration is model "
    "arithmetic over a VirtualClock; a single time.sleep or wall/"
    "monotonic reading re-couples the trajectory to host scheduling, "
    "and the 1-core CI host turns that into flaky campaigns and "
    "false straggler flags.",
    _virtual_clock_scope,
)
def check_virtual_clock_purity(ctx: FileContext) -> Iterator[Finding]:
    """Any ``time.*`` clock/sleep or ``datetime.now``-family call in a
    twin module — including the bare names when imported via
    ``from time import sleep`` — is an error.  VirtualClock methods
    (``now``/``advance``/``advance_to``) are attribute calls on model
    state and do not match."""
    # Map `from time import sleep as snooze` -> {"snooze": "time.sleep"}.
    aliased: dict[str, str] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom) and node.module in (
                "time", "datetime"):
            for alias in node.names:
                dotted = f"{node.module}.{alias.name}"
                if dotted in _REAL_CLOCK_CALLS:
                    aliased[alias.asname or alias.name] = dotted
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        dotted = aliased.get(name, name)
        if dotted in _REAL_CLOCK_CALLS:
            yield ctx.finding(
                "DML016", node,
                f"{dotted}() inside a virtual-clock path — the twin "
                "must stay pure arithmetic over VirtualClock; real "
                "sleeps/clock reads make modeled campaigns depend on "
                "host scheduling and break deterministic replay",
            )


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------

def iter_source_files(root: str | os.PathLike) -> Iterator[str]:
    """Repo-relative paths of every .py file a scan covers (package +
    tools + tests, minus fixtures and caches)."""
    root = os.fspath(root)
    for top in SCAN_DIRS:
        base = os.path.join(root, top)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            rel_dir = os.path.relpath(dirpath, root)
            if any(part in rel_dir for part in EXCLUDE_PARTS):
                dirnames[:] = []
                continue
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.relpath(
                        os.path.join(dirpath, name), root
                    ).replace(os.sep, "/")


def run_source(src: str, virtual_path: str,
               rules: Iterable[str] | None = None,
               honor_virtual_header: bool = True,
               timings: dict | None = None) -> list[Finding]:
    """Run Layer 1 over one source string as if it lived at
    ``virtual_path`` — the fixture-snippet entry point.  A
    ``# dmlcheck-virtual-path:`` header in the source overrides the
    argument (fixtures use it to opt into scoped rules); repo scans
    pass ``honor_virtual_header=False`` so findings always carry the
    REAL path the baseline matches on.  ``timings`` (rule id →
    seconds) accrues per-rule wall time across calls — the budget
    telemetry ``dmlcheck --json`` reports."""
    if honor_virtual_header:
        m = VIRTUAL_PATH_RE.search(src)
        if m:
            virtual_path = m.group(1)
    ctx = FileContext(virtual_path, src)
    out: list[Finding] = []
    for rule in RULES.values():
        if rules is not None and rule.id not in rules:
            continue
        if rule.applies(ctx.path):
            t0 = time.perf_counter()
            out.extend(rule.check(ctx))
            if timings is not None:
                timings[rule.id] = (timings.get(rule.id, 0.0)
                                    + time.perf_counter() - t0)
    return out


def run_layer1(root: str | os.PathLike,
               rules: Iterable[str] | None = None,
               files: Iterable[str] | None = None,
               timings: dict | None = None) -> list[Finding]:
    """Run every (or the selected) Layer-1 rule over the repo at
    ``root``; returns findings sorted by (file, line, rule).  Files
    that fail to parse yield a DML000 finding instead of crashing the
    scan (a syntax error in the tree is a finding, not an excuse)."""
    root = os.fspath(root)
    findings: list[Finding] = []
    for rel in (files if files is not None else iter_source_files(root)):
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as f:
                src = f.read()
        except OSError:
            continue
        try:
            findings.extend(run_source(src, rel, rules=rules,
                                       honor_virtual_header=False,
                                       timings=timings))
        except SyntaxError as e:
            findings.append(Finding(
                rule="DML000", file=rel, line=e.lineno or 0,
                message=f"file does not parse: {e.msg}", layer=1))
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings
