# dmlcheck-virtual-path: distributed_machine_learning_tpu/runtime/fixture.py
"""DML009 firing case: the SIGTERM→SystemExit drain path is eaten."""


def worker_loop(step_once):
    while True:
        try:
            step_once()
        except SystemExit:
            break                # drain signal swallowed: zombie rank
        except BaseException:
            continue             # including the abort path
