"""Pallas TPU kernels for the hot ops (flash attention).

Kernels run compiled on TPU and in interpreter mode elsewhere (the CPU
test mesh), so the same code path is exercised everywhere.
"""
