"""Fused LM-head + cross-entropy: loss without materializing the logits.

At real LM scale the ``[tokens, vocab]`` logit tensor is the single
largest activation of the whole network — batch 8 × seq 4096 × vocab
128k in fp32 is 16 GB, bigger than the model.  The reference never hits
this (its classifier head is 10-wide — ``part1/model.py:44``), but a
long-context LM framework must.  This op computes

    mean over tokens of  [ logsumexp(h·W + b) − (h·W + b)[target] ]

chunk by chunk over the vocabulary: each chunk materializes only a
``[T, chunk]`` logit block, maintains a running online logsumexp (the
same max-rescaling recurrence flash attention uses over keys), and picks
out the target logit for targets that fall inside the chunk.  Peak
activation memory drops from O(T·V) to O(T·V/num_chunks).

The chunk loop is a static Python loop over ``lax.slice`` columns of the
*original* kernel — no padded/transposed copy is ever built, XLA fuses
each slice into its matmul, and the matmul runs in the inputs' dtype
(bf16 stays on the bf16 MXU path) with fp32 accumulation
(``preferred_element_type``); only the logsumexp/softmax bookkeeping is
fp32.  The backward pass is a custom VJP that replays the same loop,
recomputing each logit block from the saved per-token logsumexp
(``probs = exp(logits − lse)``), accumulating ``dh`` and emitting
per-chunk ``dW``/``db`` — so backward peak memory matches forward.

Numerics match the unfused loss to fp32 roundoff (reduction order
differs across chunks).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30  # finite -inf stand-in (running-max init)


def _chunk_bounds(V: int, num_chunks: int) -> list[tuple[int, int]]:
    """Static (start, stop) per chunk; empty tail chunks are dropped."""
    if num_chunks < 1:
        raise ValueError(f"num_chunks must be >= 1, got {num_chunks}")
    C = -(-V // num_chunks)
    return [(s, min(s + C, V)) for s in range(0, V, C)]


def _block(h, kernel, bias, start: int, stop: int):
    """fp32 logits for vocab columns [start, stop) — the matmul runs in
    the inputs' dtype (bf16 stays bf16 on the MXU), accumulating fp32."""
    k_c = lax.slice(kernel, (0, start), (kernel.shape[0], stop))
    logits = jnp.dot(h, k_c, preferred_element_type=jnp.float32)
    return logits + lax.slice(bias, (start,), (stop,)).astype(jnp.float32)


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_linear_cross_entropy(hidden, kernel, bias, targets,
                               num_chunks: int = 8):
    """Mean cross-entropy of ``softmax(hidden @ kernel + bias)`` against
    ``targets`` without materializing the ``[T, V]`` logits.

    ``hidden``: [T, E]; ``kernel``: [E, V]; ``bias``: [V];
    ``targets``: [T] int.  ``num_chunks``: vocabulary chunks (static);
    peak logit memory is ``T × ceil(V/num_chunks)``.
    """
    loss, _ = _fused_fwd_impl(hidden, kernel, bias, targets, num_chunks)
    return loss


def _fused_fwd_impl(hidden, kernel, bias, targets, num_chunks):
    T = hidden.shape[0]
    m = jnp.full((T,), NEG_INF, jnp.float32)
    s = jnp.zeros((T,), jnp.float32)
    tgt = jnp.zeros((T,), jnp.float32)
    for start, stop in _chunk_bounds(kernel.shape[1], num_chunks):
        logits = _block(hidden, kernel, bias, start, stop)  # [T, C] fp32
        m_new = jnp.maximum(m, logits.max(axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.exp(
            logits - m_new[:, None]
        ).sum(axis=-1)
        m = m_new
        # Target logit if it falls in this chunk (one-hot contraction —
        # same TP-friendly trick as train/losses.py; out-of-range rows
        # produce an all-zero row, contributing nothing).
        one_hot = jax.nn.one_hot(targets - start, stop - start,
                                 dtype=jnp.float32)
        tgt = tgt + jnp.sum(logits * one_hot, axis=-1)
    lse = m + jnp.log(s)
    return (lse - tgt).mean(), lse


def _fused_fwd(hidden, kernel, bias, targets, num_chunks):
    loss, lse = _fused_fwd_impl(hidden, kernel, bias, targets, num_chunks)
    return loss, (hidden, kernel, bias, targets, lse)


def _fused_bwd(num_chunks, res, g):
    hidden, kernel, bias, targets, lse = res
    T = hidden.shape[0]
    scale = g / T  # d(mean)/d(per-token loss)
    dh = jnp.zeros(hidden.shape, jnp.float32)
    dk_parts, db_parts = [], []
    for start, stop in _chunk_bounds(kernel.shape[1], num_chunks):
        logits = _block(hidden, kernel, bias, start, stop)  # recomputed
        probs = jnp.exp(logits - lse[:, None])
        one_hot = jax.nn.one_hot(targets - start, stop - start,
                                 dtype=jnp.float32)
        dlogits = (probs - one_hot) * scale  # [T, C] fp32
        k_c = lax.slice(kernel, (0, start), (kernel.shape[0], stop))
        dh = dh + jnp.dot(dlogits, k_c.T.astype(jnp.float32))
        dk_parts.append(
            jnp.dot(hidden.astype(jnp.float32).T, dlogits)
        )  # [E, C]
        db_parts.append(dlogits.sum(axis=0))  # [C]
    dk = jnp.concatenate(dk_parts, axis=1)
    db = jnp.concatenate(db_parts)
    return (
        dh.astype(hidden.dtype),
        dk.astype(kernel.dtype),
        db.astype(bias.dtype),
        None,
    )


fused_linear_cross_entropy.defvjp(_fused_fwd, _fused_bwd)
