"""part2b — collective all-reduce sync (reference ``part2/2b/main.py``).

One ``dist.all_reduce(SUM)`` per parameter (``part2/2b/main.py:101-106``)
becomes one ``lax.psum`` per gradient leaf; SUM semantics (no division by
world size — SURVEY.md §2.4), batch 64/worker.
"""

from __future__ import annotations

from distributed_machine_learning_tpu.cli.common import make_flag_parser, parse_flags, run_part

BATCH_SIZE = 64  # per worker — part2/2b/main.py:31


def main(argv=None) -> None:
    args = parse_flags(make_flag_parser(__doc__), argv)
    run_part("all_reduce", per_rank_batch=BATCH_SIZE, use_bn=False, args=args)


if __name__ == "__main__":
    main()
