#!/usr/bin/env python3
"""Live / post-mortem serving-fleet status — stdlib-only, jax-free.

The serving complement of ``tools/gang_status.py`` (ISSUE 17): where
that tool renders gang health, this one renders the REQUEST view of a
serving fleet from two artifact planes:

- the coordination dir (``--gang-dir`` of ``cli/serve.py``): the
  transport snapshot (replica roles / serving epochs / drain latches /
  queue depths), the router's final ``serving`` summary, and the
  per-request ``serve_request`` health-ledger records the router
  appends at each completion — each carrying the request's full
  stage-event journey (see ``runtime/transport.py::SERVING_STAGES``);
- the telemetry dir (default ``<gang-dir>/telemetry``): the router's
  ``registry.router.json`` snapshot with the live
  ``serving_stage_latency_s{stage=...}`` histograms and fleet gauges.

Renders per-stage p50/p95/p99, per-replica compute time + skew, queue
depth / in-flight, and — with ``--slo`` objectives — the SLO burn
state replayed over the completion records (writer-clock timestamps
compared among themselves only, never against this reader's clock:
the DML001 rule).  ``--postmortem RID`` reconstructs one request's
complete event timeline — the "why was THIS request slow" debugging
workflow: every stage, who stamped it, and the rank-local delta since
that actor's previous stamp.

A fleet that took continuous deployments (ISSUE 18,
``cli/deploy.py``) additionally renders each replica's committed /
staging weight version and a "Continuous deployment" section: the
reconstructed state machine (canary / promoted / rolled_back), the
per-replica swap history, and every rollback with its reason.

Usage:  python tools/serve_status.py <gang-dir> [--telemetry DIR]
                 [--slo SPEC ...] [--postmortem RID] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
from distributed_machine_learning_tpu.runtime.transport import (  # noqa: E402,E501
    FileTransport,
)
from distributed_machine_learning_tpu.telemetry.aggregator import (  # noqa: E402,E501
    median,
    serving_stage_samples,
)
from distributed_machine_learning_tpu.telemetry.slo import (  # noqa: E402,E501
    SLOEngine,
    format_verdict,
)

# registry snapshots a serving run may have left, most specific first:
# the router's instance-tagged file, then the single-process default.
_REGISTRY_CANDIDATES = ("registry.router.json", "registry.json")


def _load_registry(telemetry_dir: str) -> dict | None:
    for name in _REGISTRY_CANDIDATES:
        path = os.path.join(telemetry_dir, name)
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
    return None


def collect(gang_dir: str, telemetry_dir: str) -> dict:
    """Everything the renderers need, as one JSON-ready dict."""
    snap = FileTransport(gang_dir).snapshot()
    health = snap["health"]
    summary = None
    requests = []
    deploy_events = []
    for e in health:
        kind = e.get("kind")
        if kind == "serving":
            summary = e
        elif kind == "serve_request":
            requests.append(e)
        elif kind in ("weight_swap", "deploy_canary", "deploy_promote",
                      "deploy_rollback", "deploy_verify_failed"):
            deploy_events.append(e)
    # The deployment state machine (ISSUE 18), reconstructed from the
    # ledger: the LAST state edge wins, swap history rides along.
    dep_state = None
    for e in deploy_events:
        dep_state = {"deploy_canary": "canary",
                     "deploy_promote": "promoted",
                     "deploy_rollback": "rolled_back",
                     "deploy_verify_failed": "verify_failed"}.get(
            e.get("kind"), dep_state)
    deployment = {
        "state": dep_state,
        "swaps": [e for e in deploy_events
                  if e.get("kind") == "weight_swap"],
        "promotions": sum(1 for e in deploy_events
                          if e.get("kind") == "deploy_promote"),
        "rollbacks": sum(1 for e in deploy_events
                         if e.get("kind") == "deploy_rollback"),
        "events": deploy_events,
    }
    # Per-replica compute intervals out of the event stream — the same
    # ``computed``-delta feed the router's straggler judgement uses.
    compute: dict[int, list[float]] = {}
    for rec in requests:
        for rank, dt in serving_stage_samples(
                rec.get("events"), stage="computed").items():
            compute.setdefault(rank, []).append(dt)
    means = {rank: sum(v) / len(v) for rank, v in compute.items() if v}
    med = median(means.values())
    replica_rows = [
        {"rank": rank, "requests": len(compute[rank]),
         "compute_mean_s": means[rank],
         "skew": (means[rank] / med) if med > 0 else None}
        for rank in sorted(means)]
    # Live per-stage quantiles from the router's registry snapshot.
    stages = {}
    gauges = {}
    reg = _load_registry(telemetry_dir)
    if reg is not None:
        for h in reg.get("histograms", ()):
            if h.get("name") == "serving_stage_latency_s":
                stage = (h.get("labels") or {}).get("stage")
                if stage:
                    stages[stage] = h
        for g in reg.get("gauges", ()):
            if g.get("name") in ("serving_queue_depth",
                                 "serving_inflight",
                                 "serving_replicas"):
                gauges[g["name"]] = g.get("value")
    return {
        "gang_dir": gang_dir,
        "serving_state": snap.get("serving"),
        "summary": summary,
        "deployment": deployment,
        "requests": requests,
        "replicas": replica_rows,
        "stages": stages,
        "gauges": gauges,
    }


def slo_replay(requests: list[dict], specs: list[str], *,
               short_window_s: float, long_window_s: float,
               burn_threshold: float) -> dict:
    """Replay the completion records through an :class:`SLOEngine`.

    Timestamps are the ROUTER's own ``time`` fields replayed in order —
    one writer's clock compared to itself, so the reader's clock never
    enters (DML001).  Covers completed requests only: admission rejects
    leave no ledger record, so the whole-run reject count lives in the
    ``serving`` summary, not here."""
    engine = SLOEngine(specs, short_window_s=short_window_s,
                       long_window_s=long_window_s,
                       burn_threshold=burn_threshold)
    rows = [r for r in requests
            if isinstance(r.get("time"), (int, float))
            and isinstance(r.get("latency_s"), (int, float))]
    for r in sorted(rows, key=lambda r: r["time"]):
        engine.observe(latency_s=r["latency_s"], now=r["time"])
    verdict = engine.verdict()
    verdict["replayed"] = len(rows)
    return verdict


def render_postmortem(status: dict, rid: str) -> str | None:
    """One request's full journey from its ``serve_request`` record, or
    None when the ledgers hold no completed record for ``rid``."""
    rec = None
    for e in status["requests"]:
        if e.get("rid") == rid:
            rec = e  # last record wins (there should be exactly one)
    if rec is None:
        return None
    lat = rec.get("latency_s")
    lines = [f"== Postmortem {rid} ==",
             f"  completed in "
             + (f"{lat * 1e3:.2f} ms" if lat is not None else "?")
             + f" after {rec.get('dispatches', '?')} dispatch(es)"]
    lines.append(f"  {'stage':>10}  {'by':>10}  {'dt':>10}  detail")
    for ev in rec.get("events") or ():
        if not isinstance(ev, dict):
            continue
        dt = ev.get("dt")
        dt_s = f"{dt * 1e3:.3f}ms" if isinstance(dt, (int, float)) \
            else "-"
        detail = "  ".join(
            f"{k}={ev[k]}" for k in sorted(ev)
            if k not in ("stage", "by", "dt"))
        lines.append(f"  {ev.get('stage', '?'):>10}  "
                     f"{ev.get('by', '?'):>10}  {dt_s:>10}  {detail}")
    return "\n".join(lines)


def render(status: dict, slo_verdict: dict | None = None) -> str:
    lines = [f"== Serving fleet {status['gang_dir']} =="]
    sv = status.get("summary")
    if sv:
        lines.append(
            f"  {sv.get('completed', 0)}/{sv.get('admitted', 0)} "
            f"completed, {sv.get('rejected', 0)} rejected, "
            f"{sv.get('evictions', 0)} eviction(s), "
            f"{sv.get('drains', 0)} drain(s); exactly-once: "
            f"{'PASS' if sv.get('exactly_once') else 'FAIL'}")
    g = status.get("gauges") or {}
    if g:
        lines.append(
            f"  live: {g.get('serving_replicas', '?')} replica(s), "
            f"queue depth {g.get('serving_queue_depth', '?')}, "
            f"{g.get('serving_inflight', '?')} in flight")
    state = status.get("serving_state") or {}
    for rank_s, rec in sorted((state.get("replicas") or {}).items(),
                              key=lambda kv: int(kv[0])):
        role = "draining" if rec.get("drain") else rec.get("role", "?")
        w = rec.get("weights") or {}
        wtxt = f", weights v{w.get('version', 0)}"
        if w.get("pending") is not None:
            wtxt += f" (staging v{w['pending']})"
        lines.append(f"  replica {rank_s}: {role}, epoch "
                     f"{rec.get('epoch', 0)}, "
                     f"{rec.get('queued', 0)} queued request(s)"
                     f"{wtxt}")
    dep = status.get("deployment") or {}
    if dep.get("events"):
        lines.append("== Continuous deployment ==")
        lines.append(
            f"  state: {dep.get('state', '?')}, "
            f"{len(dep.get('swaps') or ())} swap(s), "
            f"{dep.get('promotions', 0)} promoted, "
            f"{dep.get('rollbacks', 0)} rolled back")
        for e in dep.get("swaps") or ():
            lines.append(
                f"  swap: replica {e.get('rank', '?')} -> "
                f"v{e.get('version', '?')} "
                f"(step {e.get('step', '?')}, {e.get('why', '?')})")
        for e in dep.get("events") or ():
            if e.get("kind") == "deploy_rollback":
                lines.append(
                    f"  rollback: v{e.get('version', '?')} -> "
                    f"v{e.get('to_version', '?')}: "
                    f"{e.get('reason', '?')}")
    stages = status.get("stages") or {}
    if stages:
        lines.append("== Per-stage latency ==")
        lines.append(f"  {'stage':>10}  {'count':>6}  {'p50':>10}  "
                     f"{'p95':>10}  {'p99':>10}")
        for stage, h in sorted(stages.items()):
            lines.append(
                f"  {stage:>10}  {h.get('count', 0):>6}  "
                f"{h.get('p50', 0) * 1e3:>8.2f}ms  "
                f"{h.get('p95', 0) * 1e3:>8.2f}ms  "
                f"{h.get('p99', 0) * 1e3:>8.2f}ms")
    if status.get("replicas"):
        lines.append("== Per-replica compute ==")
        for r in status["replicas"]:
            skew = f"{r['skew']:.2f}x" if r["skew"] is not None else "-"
            lines.append(
                f"  replica {r['rank']}: {r['requests']} request(s), "
                f"mean compute {r['compute_mean_s'] * 1e3:.2f} ms, "
                f"skew {skew}")
    if slo_verdict is not None:
        lines.append(f"== SLO burn state "
                     f"({slo_verdict.get('replayed', 0)} completion(s) "
                     "replayed) ==")
        lines.append(format_verdict(slo_verdict))
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("gang_dir", help="the fleet coordination dir "
                                         "(--gang-dir of cli/serve.py)")
    parser.add_argument("--telemetry", default=None,
                        help="telemetry dir (default: "
                             "<gang-dir>/telemetry)")
    parser.add_argument("--slo", action="append", default=[],
                        metavar="SPEC",
                        help="objective to evaluate over the completion "
                             "records, e.g. p99<=250ms or "
                             "reject_ratio<=0.05 (repeatable)")
    parser.add_argument("--slo-short-window", type=float, default=5.0)
    parser.add_argument("--slo-long-window", type=float, default=60.0)
    parser.add_argument("--slo-burn-threshold", type=float, default=2.0)
    parser.add_argument("--postmortem", default=None, metavar="RID",
                        help="print one request's full event timeline")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable dump instead of tables")
    args = parser.parse_args(argv)
    if not os.path.isdir(args.gang_dir):
        print(f"not a directory: {args.gang_dir}", file=sys.stderr)
        return 2
    telemetry_dir = args.telemetry or os.path.join(args.gang_dir,
                                                   "telemetry")
    status = collect(args.gang_dir, telemetry_dir)
    if args.postmortem is not None:
        text = render_postmortem(status, args.postmortem)
        if text is None:
            print(f"no completed serve_request record for rid "
                  f"{args.postmortem!r} in {args.gang_dir} (still in "
                  "flight, rejected, or records disabled)",
                  file=sys.stderr)
            return 1
        print(text)
        return 0
    verdict = None
    if args.slo:
        verdict = slo_replay(
            status["requests"], args.slo,
            short_window_s=args.slo_short_window,
            long_window_s=args.slo_long_window,
            burn_threshold=args.slo_burn_threshold)
    if args.json:
        out = dict(status)
        out["slo"] = verdict
        print(json.dumps(out, indent=1))
    else:
        print(render(status, verdict))
    return 0


if __name__ == "__main__":
    sys.exit(main())
