# dmlcheck-virtual-path: distributed_machine_learning_tpu/runtime/netmodel_pacer.py
"""DML016 clean case: the sanctioned twin idiom — every duration is
model arithmetic, time only moves through the VirtualClock seam, and
``clock.now()``/``advance``/``advance_to`` are attribute calls on model
state (not real clocks), so the rule stays quiet."""
import threading


def modeled_step(nm, rank):
    dt = nm.step_time(rank)               # pure arithmetic pricing
    return dt


def advance_gang(nm, world):
    step_max = max(nm.step_time(r) for r in range(world))
    nm.clock.advance(step_max)            # virtual time, not a sleep
    return nm.clock.now()


def degraded_window(nm, src, dst, k, until_s):
    nm.degrade_link(src, dst, k)
    nm.clock.advance_to(until_s)
    nm.restore_link(src, dst)
    return nm.clock.now()


def guarded_mutation(nm, lock: threading.Lock, src, dst, k):
    with lock:                            # locks are fine; clocks are not
        nm.degrade_link(src, dst, k)
    return nm.degraded_links()
