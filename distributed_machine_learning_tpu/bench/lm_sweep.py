"""LM scaling sweep harness — tokens/sec/device for the LM schemes.

Round 3's sweep machinery (`bench/sweep.py`) covered only the CNN
strategies; the schemes a real pod will actually run — per-layer FSDP,
tensor parallelism, pipeline parallelism — had no harness, so a
multi-chip session would have started by writing one (VERDICT r03
item 6).  This module makes each of them a one-command sweep:

- ``fsdp_pl`` — **weak scaling over the batch**: fixed per-device
  batch, device count grows the global batch (the classic data-parallel
  weak-scaling protocol, matching the CNN sweep and the reference's
  1→4-node experiment, group25.pdf p.10).
- ``tp`` — **strong scaling at fixed problem size**: the global batch
  and model are pinned while the model axis grows; efficiency is
  tokens/sec(d) / (d · tokens/sec(1)).  (Growing the model with the
  mesh would change the program per point — the fixed-model curve is
  the one that answers "how many chips should serve this model".)
- ``pp`` — **weak scaling over depth**: ``n_layers = layers_per_stage
  × stages``, so per-device compute is fixed while the MODEL grows with
  the pipeline — pipeline parallelism's reason to exist.  Microbatches
  scale with the stage count to hold the bubble fraction
  (P−1)/(M+P−1) comparable across points.
- ``ep`` — **weak scaling over the expert axis** (VERDICT r04 item 5):
  ``n_experts = experts_per_device × devices`` and the global batch
  grows with the mesh, through the dropless grouped-EP step (explicit
  token all_to_all + ragged_dot).  Top-1 routing keeps per-TOKEN
  compute constant as experts grow, so tokens/sec/device is flat on
  ideal hardware — the efficiency norm is 1, and the shortfall is the
  genuine all_to_all + padding cost.
- ``ring`` — **weak scaling over sequence** (the long-context pod
  scheme): global ``seq = seq_len × devices`` at a fixed per-device
  chunk, ring-attention context parallelism.  Causal attention work
  per token GROWS with the global sequence, so the efficiency norm is
  FLOPs/sec/device (tokens/sec/device × modeled FLOPs/token at that
  point's length — ``utils/flops.py``), not raw token rate.

Timing: chained donated steps, per-step time from the two-point slope
(N vs 2N chained steps — fixed dispatch overhead cancels; same
methodology as bench.py / bench_lm.py, which on a tunneled chip is the
difference between measuring the step and measuring the tunnel).

Runs anywhere a mesh runs: real chips, or the virtual CPU mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=N``) where the
harness logic and the compiled sharded programs are what is being
exercised — per-device throughput on virtual devices falls with the
count by construction and is labeled as such in the dryrun.
"""

from __future__ import annotations

import argparse
import json
import time
from dataclasses import asdict, dataclass

import jax
import jax.numpy as jnp
import numpy as np

LM_SWEEP_SCHEMES = ("fsdp_pl", "tp", "pp", "ep", "ring")
# One default, shared by lm_run_point's signature and the tp auto-count
# filter, so they cannot drift.
DEFAULT_N_HEADS = 8


@dataclass
class LMScalePoint:
    """One measured point of an LM scaling sweep."""

    num_devices: int
    scheme: str
    mode: str  # "weak-batch" | "strong" | "weak-depth"
    d_model: int
    n_layers: int
    seq_len: int
    global_batch: int
    tokens_per_sec: float
    tokens_per_sec_per_device: float
    efficiency: float | None = None
    # Modeled train FLOPs per token at this point's shape (set for the
    # weak-seq ring mode, whose per-token work grows with the global
    # sequence — the efficiency norm multiplies by it).
    flops_per_token: float | None = None


def _time_chained(step, state, x, y, n: int):
    """Wall time of ``n`` chained step dispatches closed by a loss fetch.
    The state threads through (steps donate their input state), so the
    chain is the real training execution pattern."""
    t0 = time.perf_counter()
    loss = None
    for _ in range(n):
        state, loss = step(state, x, y)
    jax.block_until_ready(loss)
    float(loss)
    return time.perf_counter() - t0, state


def _per_step_time(step, state, x, y, iters: int):
    """Two-point slope: (t(2N) − t(N)) / N cancels fixed overhead."""
    state, _ = step(state, x, y)  # compile (excluded)
    # A full throwaway chain: the first post-compile chain still carries
    # one-time costs (executable load, donation buffer setup — measured
    # ~1.5× steady state on the CPU mesh) that would corrupt the slope.
    _, state = _time_chained(step, state, x, y, iters)
    t1, state = _time_chained(step, state, x, y, iters)
    t2, state = _time_chained(step, state, x, y, 2 * iters)
    slope = (t2 - t1) / iters
    avg = t2 / (2 * iters)
    # Same jitter guard as bench/harness.py::two_point_fit: a noisy t1
    # can push the slope negative (absurd throughput) or above the
    # chained average (impossible) — fall back to the average, which
    # over-counts only the fixed overhead instead of fabricating rates.
    if slope <= 0 or slope > avg:
        slope = avg
    return slope


def lm_run_point(
    scheme: str,
    num_devices: int,
    *,
    d_model: int = 256,
    n_heads: int = DEFAULT_N_HEADS,
    vocab: int = 256,
    seq_len: int = 128,
    per_device_batch: int = 4,
    global_batch: int | None = None,
    n_layers: int = 4,
    layers_per_stage: int = 2,
    experts_per_device: int = 2,
    timed_iters: int = 4,
    devices=None,
) -> LMScalePoint:
    """Measure one (scheme, device-count) point; see module docstring
    for each scheme's scaling mode."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state

    if scheme not in LM_SWEEP_SCHEMES:
        raise ValueError(
            f"scheme must be one of {LM_SWEEP_SCHEMES}, got {scheme!r}"
        )
    if num_devices < 1:
        raise ValueError(f"num_devices must be >= 1, got {num_devices}")
    if timed_iters < 1:
        raise ValueError(f"timed_iters must be >= 1, got {timed_iters}")
    rng = np.random.default_rng(0)

    if scheme == "fsdp_pl":
        from distributed_machine_learning_tpu.parallel.fsdp_perlayer import (
            make_fsdp_pl_lm_train_step,
            shard_fsdp_pl_state,
        )
        from distributed_machine_learning_tpu.train.adamw import AdamWConfig

        mode = "weak-batch"
        batch = per_device_batch * num_devices
        model = TransformerLM(
            vocab_size=vocab, d_model=d_model, n_layers=n_layers,
            n_heads=n_heads, compute_dtype=jnp.bfloat16,
        )
        mesh = make_mesh(num_devices, ("batch",), devices=devices)
        state = shard_fsdp_pl_state(
            init_lm_state(model, config=AdamWConfig()), mesh
        )
        step = make_fsdp_pl_lm_train_step(model, mesh)
        sharding = NamedSharding(mesh, P("batch", None))
        layers = n_layers
    elif scheme == "tp":
        from distributed_machine_learning_tpu.parallel.tensor_parallel import (
            make_tp_lm_train_step,
            shard_tp_state,
        )

        mode = "strong"
        if n_heads % num_devices:
            raise ValueError(
                f"tp sweep needs n_heads ({n_heads}) divisible by every "
                f"device count (got {num_devices})"
            )
        batch = global_batch or per_device_batch
        model = TransformerLM(
            vocab_size=vocab, d_model=d_model, n_layers=n_layers,
            n_heads=n_heads, compute_dtype=jnp.bfloat16,
        )
        mesh = make_mesh(
            num_devices, ("batch", "model"), (1, num_devices),
            devices=devices,
        )
        state = shard_tp_state(init_lm_state(model), mesh)
        step = make_tp_lm_train_step(model, mesh)
        sharding = NamedSharding(mesh, P("batch", None))
        layers = n_layers
    elif scheme == "ep":
        from distributed_machine_learning_tpu.models.moe import (
            MoETransformerLM,
        )
        from distributed_machine_learning_tpu.parallel.expert_parallel import (
            init_moe_state,
            make_ep_grouped_train_step,
            shard_ep_state,
        )

        mode = "weak-expert"
        batch = per_device_batch * num_devices
        model = MoETransformerLM(
            vocab_size=vocab, d_model=d_model, n_layers=n_layers,
            n_heads=n_heads, n_experts=experts_per_device * num_devices,
            moe_impl="grouped", compute_dtype=jnp.bfloat16,
        )
        mesh = make_mesh(
            num_devices, ("batch", "expert"), (1, num_devices),
            devices=devices,
        )
        state = shard_ep_state(init_moe_state(model), mesh)
        step = make_ep_grouped_train_step(model, mesh)
        # The grouped-EP step's contract: token rows shard over the
        # combined (data, expert) axes.
        sharding = NamedSharding(mesh, P(("batch", "expert"), None))
        layers = n_layers
    elif scheme == "ring":
        from distributed_machine_learning_tpu.train.lm_step import (
            make_lm_train_step,
        )

        mode = "weak-seq"
        batch = per_device_batch  # fixed global batch; the SEQUENCE grows
        seq_len = seq_len * num_devices  # seq_len acts as per-device chunk
        model = TransformerLM(
            vocab_size=vocab, d_model=d_model, n_layers=n_layers,
            n_heads=n_heads, attn_impl="ring", compute_dtype=jnp.bfloat16,
        )
        mesh = make_mesh(
            num_devices, ("batch", "seq"), (1, num_devices),
            devices=devices,
        )
        state = init_lm_state(model)
        step = make_lm_train_step(model, mesh=mesh)
        sharding = NamedSharding(mesh, P("batch", "seq"))
        layers = n_layers
    else:  # pp — weak over depth
        from distributed_machine_learning_tpu.parallel.pipeline import (
            init_pipeline_state,
            microbatch,
            shard_pp_state,
        )
        from distributed_machine_learning_tpu.parallel.pipeline_1f1b import (
            make_pp_1f1b_lm_train_step,
        )

        mode = "weak-depth"
        layers = layers_per_stage * num_devices
        microbatches = max(2, num_devices)
        batch = per_device_batch * microbatches
        model = TransformerLM(
            vocab_size=vocab, d_model=d_model, n_layers=layers,
            n_heads=n_heads, compute_dtype=jnp.bfloat16,
        )
        mesh = make_mesh(num_devices, ("pipe",), devices=devices)
        state = shard_pp_state(init_pipeline_state(model), mesh)
        step = make_pp_1f1b_lm_train_step(
            model, mesh, num_microbatches=microbatches
        )

    toks = rng.integers(0, vocab, (batch, seq_len + 1)).astype(np.int32)
    if scheme == "pp":
        # Microbatched and replicated over the pipe mesh (the step's
        # contract: every stage sees all microbatches, masked by tick).
        x, y = microbatch(toks[:, :-1], toks[:, 1:], microbatches)
        rep = NamedSharding(mesh, P())
        x, y = jax.device_put(x, rep), jax.device_put(y, rep)
    else:
        x = jax.device_put(jnp.asarray(toks[:, :-1]), sharding)
        y = jax.device_put(jnp.asarray(toks[:, 1:]), sharding)

    per_step = _per_step_time(step, state, x, y, timed_iters)
    tps = batch * seq_len / per_step
    fpt = None
    if mode == "weak-seq":
        # Per-token work grows with the global sequence (causal
        # attention); the sweep's efficiency norm needs the modeled
        # FLOPs/token at THIS length.  Embedding is a gather, not a
        # matmul — excluded, as in bench_lm.py.
        from distributed_machine_learning_tpu.utils.flops import (
            transformer_train_flops_per_token,
        )

        n_params = sum(
            int(np.prod(p.shape))
            for p in jax.tree_util.tree_leaves(state.params)
        ) - vocab * d_model
        fpt = transformer_train_flops_per_token(
            n_params, layers, d_model, seq_len, causal=True
        )
    return LMScalePoint(
        num_devices=num_devices,
        scheme=scheme,
        mode=mode,
        d_model=d_model,
        n_layers=layers,
        seq_len=seq_len,
        global_batch=batch,
        tokens_per_sec=tps,
        tokens_per_sec_per_device=tps / num_devices,
        flops_per_token=fpt,
    )


def lm_scaling_sweep(
    scheme: str,
    device_counts: list[int] | None = None,
    devices=None,
    **point_kwargs,
) -> list[LMScalePoint]:
    """Sweep device counts for one LM scheme; annotate efficiency
    against the smallest point.

    Efficiency = per-device WORK rate relative to the smallest point:
    tokens/sec/device for the fixed-model modes (fsdp_pl weak-batch, tp
    strong), tokens·layers/sec/device for pp's weak-depth mode (the
    model grows with the pipeline, so raw token rate falls ~1/d even on
    ideal hardware — see ``norm`` below)."""
    if device_counts is None:
        n = len(devices) if devices is not None else jax.device_count()
        device_counts = [d for d in (1, 2, 4, 8, 16, 32) if d <= n]
        if scheme == "tp":
            # Auto-selection must not crash the sweep mid-run at a count
            # n_heads cannot shard over (explicit counts still raise).
            heads = point_kwargs.get("n_heads", DEFAULT_N_HEADS)
            device_counts = [d for d in device_counts if heads % d == 0]
    device_counts = sorted(set(device_counts))
    if not device_counts:
        raise ValueError("device_counts is empty: nothing to sweep")
    points = [
        lm_run_point(scheme, d, devices=devices, **point_kwargs)
        for d in device_counts
    ]

    def norm(p: LMScalePoint) -> float:
        # Per-device WORK rate, not raw token rate: pp's weak-depth mode
        # grows per-token FLOPs with the model (n_layers ∝ stages), so
        # tokens/sec/device falls ~1/d on IDEAL hardware — the honest
        # per-device quantity is tokens·layers/sec/device (∝ model
        # FLOPs/sec/device).  ring's weak-seq mode grows the causal
        # attention term with the global sequence — its norm is the
        # modeled FLOPs/sec/device.  The flat modes (fsdp_pl, tp, and
        # ep — top-1 routing holds per-token compute constant as
        # experts grow) normalize by 1.
        if p.mode == "weak-seq":
            return p.tokens_per_sec_per_device * p.flops_per_token
        return p.tokens_per_sec_per_device * (
            p.n_layers if p.mode == "weak-depth" else 1
        )

    base = norm(points[0])
    for p in points:
        p.efficiency = round(norm(p) / base, 4) if base else None
    return points


def format_row(p: LMScalePoint) -> dict:
    """JSON-able row for one sweep point — the ONE formatter the CLI and
    the dryrun share, so their rows cannot drift."""
    row = asdict(p)
    row["tokens_per_sec"] = round(row["tokens_per_sec"], 1)
    row["tokens_per_sec_per_device"] = round(
        row["tokens_per_sec_per_device"], 1
    )
    return row


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--scheme", default="fsdp_pl",
                        choices=list(LM_SWEEP_SCHEMES))
    parser.add_argument("--devices", default=None, type=str,
                        help="comma-separated device counts, e.g. 1,2,4,8")
    parser.add_argument("--d-model", dest="d_model", default=256, type=int)
    parser.add_argument("--n-heads", dest="n_heads", default=8, type=int)
    parser.add_argument("--n-layers", dest="n_layers", default=4, type=int,
                        help="fsdp_pl/tp model depth (pp grows depth as "
                             "layers-per-stage x stages)")
    parser.add_argument("--layers-per-stage", dest="layers_per_stage",
                        default=2, type=int)
    parser.add_argument("--experts-per-device", dest="experts_per_device",
                        default=2, type=int,
                        help="ep mode: n_experts = this x device count")
    parser.add_argument("--seq-len", dest="seq_len", default=128, type=int,
                        help="ring mode: the PER-DEVICE chunk (global "
                             "sequence = seq-len x device count)")
    parser.add_argument("--batch-per-device", dest="per_device_batch",
                        default=4, type=int)
    parser.add_argument("--global-batch", dest="global_batch", default=None,
                        type=int, help="tp mode: the fixed global batch")
    parser.add_argument("--iters", default=4, type=int)
    args = parser.parse_args()

    counts = (
        [int(d) for d in args.devices.split(",")] if args.devices else None
    )
    points = lm_scaling_sweep(
        args.scheme,
        device_counts=counts,
        d_model=args.d_model,
        n_heads=args.n_heads,
        n_layers=args.n_layers,
        layers_per_stage=args.layers_per_stage,
        experts_per_device=args.experts_per_device,
        seq_len=args.seq_len,
        per_device_batch=args.per_device_batch,
        global_batch=args.global_batch,
        timed_iters=args.iters,
    )
    for p in points:
        print(json.dumps(format_row(p)))
    if len(points) > 1:
        summary = {
            "metric": f"lm_{args.scheme}_scaling_efficiency",
            "value": points[-1].efficiency,
            "unit": (
                f"x{points[-1].num_devices}_vs_x{points[0].num_devices}"
            ),
            "mode": points[-1].mode,
        }
        if points[-1].mode != "strong":
            # BASELINE.md north-star (>=85%) is a WEAK-scaling target;
            # attaching it to tp's fixed-problem strong-scaling curve
            # would flag healthy runs as regressions.
            summary["target"] = 0.85
        print(json.dumps(summary))


if __name__ == "__main__":
    main()
