// Native batch-assembly loader: the runtime role torch's C++ DataLoader
// (pin_memory workers — reference part2/2a/main.py:162-167) plays, built
// for the TPU host side: a worker thread gathers dataset rows into batch
// buffers ahead of the training loop behind a bounded queue, overlapping
// host memcpy/IO with device compute.
//
// C ABI (consumed by data/native_loader.py via ctypes):
//   dl_create  — start a loader over (images, labels) with a fixed epoch
//                index order and batch size; spawns the worker thread.
//   dl_next    — blocking pop of the next batch into caller buffers;
//                returns the row count (0 = end of epoch).
//   dl_destroy — stop the worker (even mid-epoch: the training loop's
//                40-iteration cap abandons epochs routinely) and free.
//
// The caller owns the dataset memory and must keep it alive for the
// handle's lifetime; batches are copied into loader-owned buffers, so
// dl_next never aliases dataset or queue memory.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct Batch {
  std::vector<uint8_t> images;
  std::vector<int32_t> labels;
  int64_t rows = 0;
};

struct Loader {
  const uint8_t* images = nullptr;
  const int32_t* labels = nullptr;
  int64_t row_bytes = 0;
  std::vector<int64_t> indices;
  int64_t batch = 0;
  size_t depth = 1;

  std::deque<Batch> queue;
  std::mutex mu;
  std::condition_variable cv_space;  // producer waits for queue space
  std::condition_variable cv_item;   // consumer waits for an item
  bool stop = false;
  bool done = false;
  std::thread worker;

  void Run() {
    const int64_t n = static_cast<int64_t>(indices.size());
    for (int64_t start = 0; start < n; start += batch) {
      const int64_t rows = std::min(batch, n - start);
      Batch b;
      b.rows = rows;
      b.images.resize(static_cast<size_t>(rows) * row_bytes);
      b.labels.resize(static_cast<size_t>(rows));
      for (int64_t i = 0; i < rows; ++i) {
        const int64_t src = indices[static_cast<size_t>(start + i)];
        std::memcpy(b.images.data() + static_cast<size_t>(i) * row_bytes,
                    images + src * row_bytes,
                    static_cast<size_t>(row_bytes));
        b.labels[static_cast<size_t>(i)] = labels[src];
      }
      std::unique_lock<std::mutex> lk(mu);
      cv_space.wait(lk, [&] { return queue.size() < depth || stop; });
      if (stop) return;
      queue.push_back(std::move(b));
      cv_item.notify_one();
    }
    std::lock_guard<std::mutex> lk(mu);
    done = true;
    cv_item.notify_all();
  }
};

}  // namespace

extern "C" {

void* dl_create(const uint8_t* images, const int32_t* labels,
                int64_t row_bytes, const int64_t* indices, int64_t n_indices,
                int64_t batch_size, int64_t prefetch_depth) {
  if (images == nullptr || labels == nullptr || indices == nullptr ||
      row_bytes <= 0 || n_indices < 0 || batch_size <= 0) {
    return nullptr;
  }
  auto* l = new Loader();
  l->images = images;
  l->labels = labels;
  l->row_bytes = row_bytes;
  l->indices.assign(indices, indices + n_indices);
  l->batch = batch_size;
  l->depth = static_cast<size_t>(std::max<int64_t>(1, prefetch_depth));
  l->worker = std::thread([l] { l->Run(); });
  return l;
}

int64_t dl_next(void* handle, uint8_t* out_images, int32_t* out_labels) {
  auto* l = static_cast<Loader*>(handle);
  Batch b;
  {
    std::unique_lock<std::mutex> lk(l->mu);
    l->cv_item.wait(lk, [&] { return !l->queue.empty() || l->done; });
    if (l->queue.empty()) return 0;
    b = std::move(l->queue.front());
    l->queue.pop_front();
    l->cv_space.notify_one();
  }
  std::memcpy(out_images, b.images.data(), b.images.size());
  std::memcpy(out_labels, b.labels.data(), b.rows * sizeof(int32_t));
  return b.rows;
}

void dl_destroy(void* handle) {
  auto* l = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> lk(l->mu);
    l->stop = true;
    l->cv_space.notify_all();
    l->cv_item.notify_all();
  }
  if (l->worker.joinable()) l->worker.join();
  delete l;
}

}  // extern "C"
