# dmlcheck-virtual-path: distributed_machine_learning_tpu/train/fixture.py
"""DML005 clean case: the verification error reaches a consumer (log +
fallback), and handlers name what they catch."""
import logging


def restore_with_fallback(path, restore, CheckpointVerifyError, events):
    try:
        return restore(path)
    except CheckpointVerifyError as e:
        logging.warning("checkpoint %s failed verification: %s", path, e)
        events.ckpt_fallbacks += 1
        return restore(path + ".bak")
    except OSError:
        return None
