"""Mixture-of-Experts transformer (Switch-style top-1 routing).

Model family beyond the reference (EP/MoE absent — SURVEY.md §2.3), built
for expert parallelism the GSPMD way: every expert-owned parameter carries
a leading ``[n_experts, ...]`` axis, routing is expressed as static-shape
einsums against a dispatch one-hot (no gather/scatter, no dynamic shapes),
and when ``parallel/expert_parallel.py`` shards that leading axis over the
mesh's ``expert`` axis, XLA's partitioner turns the dispatch/combine
einsums into the token all-to-all — Switch Transformer's comm pattern,
inserted by the compiler.

Capacity semantics (Switch): each expert processes at most
``capacity = ceil(tokens/n_experts · capacity_factor)`` tokens per batch;
overflow tokens are dropped (their MLP output is zero and they pass
through the residual unchanged — exactly Switch's overflow behavior).
The router's load-balancing auxiliary loss (Switch eq. 4:
``E · Σ_e f_e·P_e``) is sown into the ``losses`` collection; the MoE train
step adds it with weight ``aux_loss_weight``.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

class MoEMLP(nn.Module):
    """Top-1 routed expert MLP over [B, T, D] activations.

    Two compute paths behind one routing front-end (``moe_impl``):

    - ``"einsum"`` (default): Switch-style capacity + overflow drops via
      static one-hot dispatch/combine einsums — the GSPMD-shardable form
      whose E axis ``parallel/expert_parallel.py`` shards to get the
      token all-to-all.
    - ``"grouped"``: dropless sort + ``lax.ragged_dot`` grouped matmuls
      (``ops/grouped.py``) — no capacity, no O(N²·D) dispatch FLOPs;
      the fast path on a single device or under shard_map DP, where no
      expert-axis partitioning is in play.
    """

    n_experts: int
    d_ff: int
    capacity_factor: float = 1.25
    compute_dtype: Any = jnp.float32
    moe_impl: str = "einsum"
    # Manual expert parallelism (shard_map context): when ``expert_axis``
    # is set, this module's expert params are declared at their LOCAL
    # shard shape [E/ep, ...] and the grouped compute path dispatches
    # token rows to their owner device with an explicit all_to_all
    # (ops/grouped.py::grouped_expert_mlp_ep).  ``token_axes`` names
    # every mesh axis the token rows are sharded over, so the Switch aux
    # loss is computed from GLOBAL routing statistics (pmean'd fractions)
    # — numerically the same aux the unsharded model computes.
    expert_axis: str | None = None
    token_axes: tuple = ()
    # Manual-EP send-slot bound (ADVICE r4; ops/grouped.py): None =
    # N_local slots per owner (provably dropless, ~ep× the useful
    # all-to-all rows on a balanced router); an int bounds the wire
    # bytes at Switch-style per-owner overflow drops.
    ep_slots_per_owner: int | None = None
    # Dropless routing regardless of capacity_factor.  Serving sets
    # this: Switch's capacity drop is a TRAINING-time load-balancing
    # mechanism whose drop pattern depends on the batch shape — a
    # decode step's N is B·1, so per-expert capacity collapses and two
    # batch rows routing to one expert would silently drop a token,
    # diverging the served stream from the trained model.  Dropless
    # compute runs the GROUPED path (sort + ragged_dot) regardless of
    # ``moe_impl``: it is dropless with no one-hot, so a served prompt
    # prefill costs O(N·D) dispatch instead of the einsum's O(N²·E)
    # one-hot tensors (a multi-thousand-token prompt under the einsum
    # dispatch would OOM on the [N, E, N] slot one-hot — ADVICE r4).
    dropless: bool = False
    # "int8" = weight-only quantized expert serving (dropless/decode
    # only): expert weights are int8 with per-expert per-output-channel
    # scales, read through the scale-folded ragged_dot
    # (ops/grouped.py::grouped_expert_mlp).  The router stays f32 —
    # routing decisions are argmax ties waiting to happen, and its
    # [D, E] matmul has no bandwidth to win.
    weight_quant: str | None = None
    # Manual Megatron TP for DECODE (make_tp_generate_fn's shard_map):
    # this module is then configured at its LOCAL expert width
    # (d_ff = F/tp — the column/row split applied per expert), the
    # router runs replicated (identical routing on every device), and
    # the psum below completes the per-expert row-parallel w_out
    # (b_out pre-divided by tp — tp_decode_params).  Serving-only.
    tp_axis: str | None = None

    @nn.compact
    def __call__(self, x):
        if self.moe_impl not in ("einsum", "grouped"):
            raise ValueError(
                f"moe_impl must be 'einsum' or 'grouped', got {self.moe_impl!r}"
            )
        if self.expert_axis is not None and self.moe_impl != "grouped":
            raise ValueError(
                "expert_axis (the manual shard_map EP path) requires "
                "moe_impl='grouped'; einsum EP is the GSPMD step "
                "(parallel/expert_parallel.py::make_ep_train_step)"
            )
        if self.weight_quant not in (None, "int8"):
            raise ValueError(
                f"weight_quant must be None or 'int8', got "
                f"{self.weight_quant!r}"
            )
        if self.weight_quant is not None and not self.dropless:
            raise ValueError(
                "weight_quant is a serving feature (int8 experts are not "
                "trainable); it requires the dropless serving path "
                "(decode=True — inference/generate.py clones it on)"
            )
        if self.ep_slots_per_owner is not None and self.expert_axis is None:
            raise ValueError(
                "ep_slots_per_owner bounds the manual-EP dispatch "
                "all-to-all; it requires expert_axis (the shard_map EP "
                "path) — without it the grouped path is dropless and "
                "the bound would be silently ignored"
            )
        if self.weight_quant is not None and self.expert_axis is not None:
            raise NotImplementedError(
                "int8 expert serving is single-host (no manual-EP "
                "shard_map decode path exists to quantize)"
            )
        if self.tp_axis is not None and not self.dropless:
            raise ValueError(
                "tp_axis is the manual TP-decode wiring (serving only); "
                "training-time expert parallelism is the EP step "
                "(parallel/expert_parallel.py)"
            )
        if self.tp_axis is not None and self.expert_axis is not None:
            raise NotImplementedError(
                "TP decode and manual-EP shard_map do not compose (one "
                "shard_map program each); shard experts' d_ff via tp"
            )
        B, T, D = x.shape
        N = B * T
        E = self.n_experts
        tokens = x.reshape(N, D)

        # Router in fp32: small matmul, precision matters for argmax ties.
        gate = nn.Dense(E, dtype=jnp.float32, name="router")(
            tokens.astype(jnp.float32)
        )
        probs = jax.nn.softmax(gate, axis=-1)  # [N, E]
        expert_idx = jnp.argmax(probs, axis=-1)  # [N]
        expert_prob = jnp.max(probs, axis=-1)  # [N]
        onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [N, E]

        # Switch aux loss: E · Σ_e (token fraction)·(mean router prob).
        # Under manual sharding the fractions pmean over every token-
        # sharded axis first, so the sown scalar equals the global-batch
        # aux on every device (and the einsum-EP / single-device value).
        frac = onehot.mean(axis=0)
        mean_prob = probs.mean(axis=0)
        if self.token_axes:
            from jax import lax

            frac = lax.pmean(frac, self.token_axes)
            mean_prob = lax.pmean(mean_prob, self.token_axes)
        self.sow("losses", "load_balancing", E * jnp.sum(frac * mean_prob))

        dt = self.compute_dtype
        if self.expert_axis is not None:
            from jax import lax

            ep = lax.axis_size(self.expert_axis)
            if E % ep:
                raise ValueError(
                    f"n_experts={E} must divide over expert axis size {ep}"
                )
            e_param = E // ep  # params declared at the LOCAL shard shape
        else:
            e_param = E
        if self.weight_quant == "int8":
            # Serving layout (quantize_lm_params writes it): int8 expert
            # kernels + per-(expert, out-channel) f32 scales; biases keep
            # the unquantized shape.  Zeros/ones inits — real values come
            # from the converted checkpoint.
            w_in = self.param(
                "w_in_q", nn.initializers.zeros, (e_param, D, self.d_ff),
                jnp.int8,
            )
            w_in_scale = self.param(
                "w_in_scale", nn.initializers.ones, (e_param, self.d_ff),
                jnp.float32,
            )
            w_out = self.param(
                "w_out_q", nn.initializers.zeros, (e_param, self.d_ff, D),
                jnp.int8,
            )
            w_out_scale = self.param(
                "w_out_scale", nn.initializers.ones, (e_param, D),
                jnp.float32,
            )
        else:
            w_in = self.param(
                "w_in", nn.initializers.lecun_normal(), (e_param, D, self.d_ff)
            )
            w_out = self.param(
                "w_out", nn.initializers.lecun_normal(), (e_param, self.d_ff, D)
            )
            w_in_scale = w_out_scale = None
        b_in = self.param("b_in", nn.initializers.zeros, (e_param, self.d_ff))
        b_out = self.param("b_out", nn.initializers.zeros, (e_param, D))

        if self.expert_axis is not None:
            from distributed_machine_learning_tpu.ops.grouped import (
                grouped_expert_mlp_ep,
            )

            y = grouped_expert_mlp_ep(
                tokens.astype(dt), expert_idx, w_in, b_in, w_out, b_out,
                expert_axis=self.expert_axis, n_experts_global=E,
                slots_per_owner=self.ep_slots_per_owner,
            )
            y = y * expert_prob[:, None].astype(dt)
            return y.reshape(B, T, D)

        # Serving (dropless) always computes through the grouped path —
        # see the ``dropless`` field note: same dropless math as
        # "einsum with capacity=N" minus the O(N²·E) one-hots, and the
        # only expert path the int8 serving scales are wired through.
        if self.moe_impl == "grouped" or self.dropless:
            from distributed_machine_learning_tpu.ops.grouped import (
                grouped_expert_mlp,
            )

            y = grouped_expert_mlp(
                tokens.astype(dt), expert_idx, w_in, b_in, w_out, b_out,
                w_in_scale=w_in_scale, w_out_scale=w_out_scale,
            )
            y = y * expert_prob[:, None].astype(dt)
            if self.tp_axis is not None:
                # Megatron's second g-collective, per expert: w_out is
                # row-parallel over the local d_ff slice (b_out and the
                # router-prob scale commute with the sum — both are
                # identical across devices).
                from jax import lax

                y = lax.psum(y, self.tp_axis)
            return y.reshape(B, T, D)

        # Position of each token within its expert's queue; drop overflow.
        capacity = max(1, math.ceil(N / E * self.capacity_factor))
        pos = jnp.cumsum(onehot, axis=0) * onehot  # 1-based where routed
        within = (pos > 0) & (pos <= capacity)
        slot = jax.nn.one_hot(
            (pos - 1).clip(0).astype(jnp.int32), capacity, dtype=jnp.float32
        )  # [N, E, C]
        dmask = slot * within.astype(jnp.float32)[..., None]  # [N, E, C]

        # Dispatch → expert FFN → combine: three static einsums whose E axis
        # shards over the mesh (the all_to_all lives inside the first/last).
        xe = jnp.einsum("nd,nec->ecd", tokens.astype(dt), dmask.astype(dt))
        h = nn.gelu(
            jnp.einsum("ecd,edf->ecf", xe, w_in.astype(dt))
            + b_in.astype(dt)[:, None, :]
        )
        ye = jnp.einsum("ecf,efd->ecd", h, w_out.astype(dt)) + b_out.astype(dt)[
            :, None, :
        ]
        y = jnp.einsum("ecd,nec->nd", ye, dmask.astype(dt))
        y = y * expert_prob[:, None].astype(dt)  # router-scaled (Switch)
        return y.reshape(B, T, D)


# Attention impls that need no sequence mesh axis — the set both the
# model's guard and make_ep_train_step's guard accept.
SEQ_LOCAL_ATTN_IMPLS = ("dense", "flash", "auto")
# The sequence-SHARDED impls (MoE × context parallelism): one constant so
# the model's RoPE-offset branch and the step builders can never disagree
# about which impls shard the sequence.
SEQ_SHARDED_ATTN_IMPLS = ("ring", "ring_flash", "ulysses")


def _moe_block(model: "MoETransformerLM", name: str) -> "nn.Module":
    """A transformer Block whose MLP is the routed expert mixture — the
    shared ``models.transformer.Block`` wiring, not a copy."""
    from distributed_machine_learning_tpu.models.transformer import Block

    return Block(
        n_heads=model.n_heads,
        n_kv_heads=model.n_kv_heads,
        d_ff=model.d_ff or 4 * model.d_model,
        attn_impl=model.attn_impl,
        seq_axis=model.seq_axis,
        compute_dtype=model.compute_dtype,
        flash_mesh=model.flash_mesh,
        flash_batch_axis=model.flash_batch_axis,
        # Selective remat (models/transformer.py::_mlp_sublayer wraps
        # the mlp_factory too): LN2 + the routed expert MLP recompute
        # in backward; attention residuals stay saved.
        remat_mlp=model.remat,
        decode=model.decode,
        kv_cache_dtype=model.kv_cache_dtype,
        decode_continuation=model.decode_continuation,
        # Attention projections follow the same int8 serving story as
        # the dense LM (ops/quant.py::QuantDenseGeneral).
        weight_quant=model.weight_quant,
        # Manual TP decode: attention psums ride the shared Block
        # wiring; head_dim pins the GLOBAL per-head width.
        tp_axis=model.tp_axis,
        head_dim=model.head_dim,
        mlp_factory=lambda: MoEMLP(
            n_experts=model.n_experts,
            d_ff=model.d_ff or 4 * model.d_model,
            capacity_factor=model.capacity_factor,
            compute_dtype=model.compute_dtype,
            moe_impl=model.moe_impl,
            expert_axis=model.expert_axis,
            token_axes=model.token_axes,
            ep_slots_per_owner=model.ep_slots_per_owner,
            # Serving routes dropless (see MoEMLP.dropless), through the
            # grouped sort+ragged_dot compute path.
            dropless=model.decode,
            weight_quant=model.weight_quant,
            tp_axis=model.tp_axis,
            name="moe",
        ),
        name=name,
    )


class MoETransformerLM(nn.Module):
    """Decoder-only LM with a routed expert MLP in every block."""

    vocab_size: int
    d_model: int = 256
    n_layers: int = 4
    n_heads: int = 8
    n_experts: int = 8
    d_ff: int | None = None
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    compute_dtype: Any = jnp.float32
    # "einsum" (capacity + drops, EP-shardable) or "grouped" (dropless
    # ragged_dot; composes with real EP via the manual shard_map step).
    moe_impl: str = "einsum"
    # dense / flash / auto (sequence-local kernels) anywhere; the
    # sequence-SHARDED impls (ring/ring_flash/ulysses) additionally
    # require the manual MoE × context-parallel step
    # (parallel/expert_parallel.py::make_ep_grouped_train_step with
    # seq_axis) — a mesh whose ``seq_axis`` appears in ``token_axes``.
    attn_impl: str = "dense"
    seq_axis: str = "seq"
    # Flash-under-GSPMD composition; see ``transformer.Attention``.
    flash_mesh: Any = None
    flash_batch_axis: str = "batch"
    # Manual shard_map EP (see ``MoEMLP.expert_axis``): the step builder
    # (parallel/expert_parallel.py::make_ep_grouped_train_step) clones
    # the model with these set; user code leaves them None/().
    expert_axis: str | None = None
    token_axes: tuple = ()
    # Manual-EP send-slot bound (see ``MoEMLP.ep_slots_per_owner``).
    ep_slots_per_owner: int | None = None
    # Grouped-query attention (see ``transformer.Attention``); None =
    # classic MHA with the fused qkv layout.
    n_kv_heads: int | None = None
    # Selective rematerialization: checkpoint LN2 + the expert MLP of
    # every block (the "mlp" policy — attention residuals stay saved,
    # backward never re-runs attention; models/transformer.py).  The
    # long-context enabler for MoE exactly as for the dense LM.
    remat: bool = False
    # KV-cached autoregressive serving, exactly as TransformerLM: the
    # attention caches live in the shared Block; the router runs
    # per-token, so routed expert compute needs no cache at all.
    # ``inference/generate.py`` clones these on.
    decode: bool = False
    kv_cache_dtype: Any = None
    decode_continuation: bool = False
    # Per-row cache frontiers (batched speculative decoding) — same
    # contract as ``TransformerLM.decode_batched_frontier``.
    decode_batched_frontier: bool = False
    # Manual Megatron TP for DECODE (``tp_local_decode_clone`` sets
    # these): attention heads/KV cache and every expert's d_ff shard
    # over the model axis; embed/router/lm_head/LayerNorms replicate.
    # Same contract as ``TransformerLM.tp_axis``/``head_dim``.
    tp_axis: str | None = None
    head_dim: int | None = None
    # "int8" = weight-only quantized serving (decode only): attention
    # projections and the lm_head through QuantDenseGeneral, expert
    # weights through the scale-folded ragged_dot (``MoEMLP``); params
    # from ``ops.quant.quantize_lm_params`` (it recognizes the expert
    # leaves).  The router stays f32.
    weight_quant: str | None = None

    @nn.compact
    def __call__(self, tokens, *, train: bool = False):
        del train
        if self.weight_quant is not None and not self.decode:
            raise ValueError(
                "weight_quant is a serving-decode feature (int8 weights "
                "are not trainable); clone with decode=True — "
                "inference/generate.py does this"
            )
        if self.tp_axis is not None and not self.decode:
            raise ValueError(
                "tp_axis is the manual TP-decode wiring "
                "(make_tp_generate_fn); training-time parallelism for "
                "MoE is the EP step (parallel/expert_parallel.py)"
            )
        seq_sharded = self.seq_axis in self.token_axes
        if self.attn_impl not in SEQ_LOCAL_ATTN_IMPLS and not seq_sharded:
            raise NotImplementedError(
                "MoETransformerLM runs the sequence-local attention "
                "kernels (dense/flash/auto) under plain apply; the "
                "sequence-sharded impls (ring/ring_flash/ulysses) need "
                "the MoE x context-parallel step, which clones the model "
                "with the seq axis in token_axes "
                "(parallel/expert_parallel.py::make_ep_grouped_train_step)"
            )
        B, L = tokens.shape
        if self.decode:
            if self.attn_impl != "dense":
                raise ValueError(
                    "decode mode runs dense cached attention; clone the "
                    'model with attn_impl="dense" (generate.py does this)'
                )
            # Autoregressive position tracking — one counter for the
            # stack (or one per ROW under decode_batched_frontier),
            # same contract as TransformerLM.
            if self.decode_batched_frontier:
                idx = self.variable(
                    "cache", "idx", lambda: jnp.zeros((B,), jnp.int32)
                )
                start = idx.value  # [B]
                positions = start[:, None] + jnp.arange(L)[None, :]
            else:
                idx = self.variable(
                    "cache", "idx", lambda: jnp.zeros((), jnp.int32)
                )
                start = idx.value
                positions = start + jnp.arange(L)
            if not self.is_initializing():
                idx.value = start + L
        elif self.attn_impl in SEQ_SHARDED_ATTN_IMPLS:
            # Sequence-sharded: this device holds chunk axis_index(seq)
            # of the global sequence — same RoPE offset rule as
            # TransformerLM, so sharded and unsharded logits match.
            from jax import lax

            offset = lax.axis_index(self.seq_axis) * L
            positions = offset + jnp.arange(L)
        else:
            positions = jnp.arange(L)
        x = nn.Embed(
            self.vocab_size, self.d_model, dtype=self.compute_dtype, name="embed"
        )(tokens)
        for i in range(self.n_layers):
            x = _moe_block(self, name=f"block_{i}")(x, positions)
        x = nn.LayerNorm(dtype=self.compute_dtype, name="ln_f")(x)
        if self.weight_quant == "int8":
            from distributed_machine_learning_tpu.ops.quant import (
                QuantDenseGeneral,
            )

            logits = QuantDenseGeneral(
                out_features=(self.vocab_size,),
                compute_dtype=self.compute_dtype, name="lm_head",
            )(x)
        else:
            logits = nn.Dense(
                self.vocab_size, dtype=self.compute_dtype, name="lm_head"
            )(x)
        return logits.astype(jnp.float32)
