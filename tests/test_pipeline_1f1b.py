"""1F1B pipeline schedule: update-equivalence vs GPipe.

The 1F1B step hand-writes the backward (per-microbatch vjp, cotangents
ppermuted upstream) — the property that matters is that it computes
EXACTLY the same thing as ``jax.grad`` of the GPipe forward: same loss,
same parameter updates, for microbatch counts below, at, and above the
stage count.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.runtime.mesh import make_mesh
from distributed_machine_learning_tpu.parallel.pipeline import (
    init_pipeline_state,
    make_pp_lm_train_step,
    microbatch,
    shard_pp_state,
)
from distributed_machine_learning_tpu.parallel.pipeline_1f1b import (
    make_pp_1f1b_lm_train_step,
)
from distributed_machine_learning_tpu.train.adamw import AdamWConfig


def _pipe_mesh():
    return make_mesh(8, axis_names=("pipe",))


def _model():
    return TransformerLM(vocab_size=64, d_model=16, n_layers=8, n_heads=2,
                         attn_impl="dense")


def _batch(batch=8, seq=12):
    rng = np.random.default_rng(11)
    toks = rng.integers(0, 64, (batch, seq + 1)).astype(np.int32)
    return jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])


@pytest.mark.parametrize(
    "m",
    # m=2 is the default-run keystone; m=8 is the deep variant (own
    # ~8s XLA compile) and joins the existing many-microbatches slow
    # case under -m "".
    [2, pytest.param(8, marks=pytest.mark.slow)],
)
def test_1f1b_matches_gpipe(m):
    """M < P and M == P: identical loss and updates, multiple steps."""
    model = _model()
    x, y = _batch()
    xs, ys = microbatch(x, y, m)

    g_state = shard_pp_state(
        init_pipeline_state(model, config=AdamWConfig()), _pipe_mesh())
    g_step = make_pp_lm_train_step(model, _pipe_mesh(), m)
    f_state = shard_pp_state(
        init_pipeline_state(model, config=AdamWConfig()), _pipe_mesh())
    f_step = make_pp_1f1b_lm_train_step(model, _pipe_mesh(), m)

    for _ in range(2):
        g_state, g_loss = g_step(g_state, xs, ys)
        f_state, f_loss = f_step(f_state, xs, ys)
        np.testing.assert_allclose(float(f_loss), float(g_loss),
                                   rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(f_state.params),
                    jax.tree_util.tree_leaves(g_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-6)


@pytest.mark.slow
def test_1f1b_matches_gpipe_many_microbatches():
    """M > P — the regime 1F1B exists for (in-flight stays O(P))."""
    model = _model()
    x, y = _batch(batch=16)
    xs, ys = microbatch(x, y, 16)
    g_state = shard_pp_state(init_pipeline_state(model), _pipe_mesh())
    g_step = make_pp_lm_train_step(model, _pipe_mesh(), 16)
    f_state = shard_pp_state(init_pipeline_state(model), _pipe_mesh())
    f_step = make_pp_1f1b_lm_train_step(model, _pipe_mesh(), 16)
    g_state, g_loss = g_step(g_state, xs, ys)
    f_state, f_loss = f_step(f_state, xs, ys)
    np.testing.assert_allclose(float(f_loss), float(g_loss),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(f_state.params),
                    jax.tree_util.tree_leaves(g_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-6)


def test_1f1b_guards():
    with pytest.raises(ValueError, match="dense"):
        make_pp_1f1b_lm_train_step(
            TransformerLM(vocab_size=64, d_model=16, n_layers=8, n_heads=2,
                          attn_impl="ring"),
            _pipe_mesh(), 2,
        )
    with pytest.raises(ValueError, match="divide evenly"):
        make_pp_1f1b_lm_train_step(
            TransformerLM(vocab_size=64, d_model=16, n_layers=6, n_heads=2,
                          attn_impl="dense"),
            _pipe_mesh(), 2,
        )
