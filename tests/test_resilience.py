"""The self-healing runtime, end to end.

Detection (runtime/resilience.py): the watchdog must catch a stalled
step, the preemption handler must turn SIGTERM into a clean
stop-at-step-boundary, and the training loop must honor both.

Recovery (the skip/retry/restart ladder): the non-finite-gradient guard
skips an update without touching state, the retrying data path survives
iterator deaths, and the supervisor (runtime/supervisor.py) restores the
newest complete checkpoint after stalls/crashes/kills.  The keystone is
the chaos test: one supervised run with a NaN gradient, a loader raise,
a stall, and a kill-mid-checkpoint injected must finish at the same step
count with BIT-IDENTICAL params to a fault-free run of the same seed
(minus the guard-skipped batch) — and every injected fault class must
show up in the resilience counters, because a recovery nobody can see is
indistinguishable from a fault that never fired."""

import os
import signal
import time

import numpy as np
import pytest

from distributed_machine_learning_tpu.data.retry import (
    RetryPolicy,
    retry_batches,
)
from distributed_machine_learning_tpu.runtime.faults import (
    FaultEvents,
    FaultInjector,
    InjectedFault,
    InjectedKill,
)
from distributed_machine_learning_tpu.runtime.resilience import (
    PreemptionHandler,
    Watchdog,
)
from distributed_machine_learning_tpu.runtime.supervisor import (
    RaisingWatchdog,
    StallError,
    run_attempts,
    supervised_train,
)


def test_watchdog_fires_on_stall():
    fired = []
    with Watchdog(timeout_s=0.2, on_stall=fired.append, poll_s=0.05) as wd:
        time.sleep(0.6)
    assert wd.stalled
    assert fired and fired[0] >= 0.2


def test_watchdog_beats_prevent_stall():
    fired = []
    with Watchdog(timeout_s=0.4, on_stall=fired.append, poll_s=0.05) as wd:
        for _ in range(6):
            time.sleep(0.1)
            wd.beat()
    assert not wd.stalled
    assert not fired


def test_watchdog_rejects_bad_timeout():
    with pytest.raises(ValueError):
        Watchdog(timeout_s=0)


def test_preemption_handler_catches_sigterm():
    with PreemptionHandler() as handler:
        assert not handler()
        os.kill(os.getpid(), signal.SIGTERM)
        # Signal delivery is synchronous-enough on the main thread: the
        # handler runs before the next bytecode boundary completes.
        time.sleep(0.05)
        assert handler()
    # Outside the context, the previous disposition is restored.
    assert signal.getsignal(signal.SIGTERM) not in (handler._handle,)


def test_preemption_restores_previous_handler():
    prev = signal.getsignal(signal.SIGTERM)
    h = PreemptionHandler().install()
    h.uninstall()
    assert signal.getsignal(signal.SIGTERM) is prev


def test_train_epoch_stops_at_boundary_and_beats_watchdog(rng):
    # A tiny real train loop: stop requested after the 3rd step must end
    # the epoch with exactly 3 updates applied and consistent state.
    from distributed_machine_learning_tpu.cli.common import init_model_and_state
    from distributed_machine_learning_tpu.models.vgg import VGGTest
    from distributed_machine_learning_tpu.train.loop import train_epoch
    from distributed_machine_learning_tpu.train.step import make_train_step

    model = VGGTest(use_bn=False)
    state = init_model_and_state(model)
    step = make_train_step(model, augment=False)

    def batches():
        while True:
            yield (rng.integers(0, 256, (2, 32, 32, 3)).astype(np.uint8),
                   rng.integers(0, 10, 2).astype(np.int32))

    calls = {"n": 0}

    def stop():
        return calls["n"] >= 3

    real_step = step

    def counting_step(s, x, y):
        calls["n"] += 1
        return real_step(s, x, y)

    wd = Watchdog(timeout_s=60).start()
    state, _ = train_epoch(
        counting_step, state, batches(), max_iters=10, stop=stop,
        watchdog=wd,
    )
    wd.stop()
    assert calls["n"] == 3
    assert int(state.step) == 3
    assert not wd.stalled


def test_agree_stop_single_process():
    from distributed_machine_learning_tpu.runtime.resilience import agree_stop

    assert agree_stop(True) is True
    assert agree_stop(False) is False


def test_periodic_agree_stop_single_process_is_immediate():
    from distributed_machine_learning_tpu.runtime.resilience import (
        periodic_agree_stop,
    )

    flag = {"v": False}
    stop = periodic_agree_stop(lambda: flag["v"], every=10)
    assert not stop()
    flag["v"] = True
    # Single-process forces every=1: honored on the very next poll,
    # and sticky afterwards.
    assert stop()
    flag["v"] = False
    assert stop()


def test_periodic_agree_stop_validates_every():
    import pytest

    from distributed_machine_learning_tpu.runtime.resilience import (
        periodic_agree_stop,
    )

    with pytest.raises(ValueError):
        periodic_agree_stop(lambda: False, every=0)


# ---------------------------------------------------------------------------
# Watchdog suspension + stall escalation (runtime/supervisor.py)
# ---------------------------------------------------------------------------


def test_watchdog_suspend_stops_the_clock():
    # A checkpoint save / eval longer than the timeout must NOT be
    # declared a stall — under --resume auto that would burn a restart
    # per save on a perfectly healthy run.
    fired = []
    with Watchdog(timeout_s=0.3, on_stall=fired.append, poll_s=0.05) as wd:
        with wd.suspend():
            time.sleep(0.6)
        time.sleep(0.1)  # post-suspend: the exit beat granted a window
    assert not wd.stalled
    assert not fired


def test_watchdog_suspend_is_reentrant():
    with Watchdog(timeout_s=0.2, poll_s=0.05) as wd:
        with wd.suspend(), wd.suspend():
            time.sleep(0.45)
    assert not wd.stalled


def test_raising_watchdog_escalates_at_the_next_beat():
    events = FaultEvents()
    wd = RaisingWatchdog(0.2, events, poll_s=0.05).start()
    try:
        wd.beat()  # healthy beat passes
        time.sleep(0.5)
        with pytest.raises(StallError):
            wd.beat()  # first beat after the declared stall raises
    finally:
        wd.stop()
    assert events.stalls == 1


def test_train_epoch_entry_beat_refreshes_a_stale_clock():
    # The loop beats once BEFORE pulling batch 0, so a slow setup phase
    # (compile, restore) can't eat the first batch's timeout window.
    from distributed_machine_learning_tpu.train.loop import train_epoch

    fired = []
    wd = Watchdog(timeout_s=0.3, on_stall=fired.append, poll_s=0.05).start()
    wd._last_beat -= 10.0  # pretend setup burned far more than the window

    def slow_first_batch():
        time.sleep(0.15)  # < timeout: fine IF the window was refreshed
        yield from ()

    class S:
        step = 0

    out, _ = train_epoch(
        lambda s, x, y: (s, 0.0), S(), slow_first_batch(), max_iters=1,
        watchdog=wd,
    )
    wd.stop()
    assert not fired and not wd.stalled


def test_loader_hanging_on_first_batch_is_caught_as_a_stall():
    from distributed_machine_learning_tpu.train.loop import train_epoch

    fired = []
    wd = Watchdog(timeout_s=0.2, on_stall=fired.append, poll_s=0.05).start()

    def hanging():
        time.sleep(0.6)  # past the timeout: a batch-0 hang, not setup
        yield from ()

    class S:
        step = 0

    train_epoch(lambda s, x, y: (s, 0.0), S(), hanging(), max_iters=1,
                watchdog=wd)
    wd.stop()
    assert wd.stalled and fired


def test_train_epoch_until_step_counts_applied_updates():
    # until_step is an APPLIED-updates target: a step that leaves the
    # counter unchanged (the guard's skip) consumes a batch but does not
    # count, so the epoch pulls further data to reach the target.
    from distributed_machine_learning_tpu.train.loop import train_epoch

    class S:
        def __init__(self, step):
            self.step = step

    consumed = []

    def batches():
        for i in range(100):
            consumed.append(i)
            yield (i, i)

    def step_skipping_batch_1(s, x, y):
        return (S(s.step) if x == 1 else S(s.step + 1)), 0.0

    events = FaultEvents()
    out, _ = train_epoch(
        step_skipping_batch_1, S(0), batches(), max_iters=10**9,
        until_step=3, events=events,
    )
    assert out.step == 3
    assert consumed == [0, 1, 2, 3]  # four batches for three updates
    assert events.skipped_steps == 1


# ---------------------------------------------------------------------------
# Fault injector (runtime/faults.py)
# ---------------------------------------------------------------------------


def test_fault_spec_parses_all_classes():
    inj = FaultInjector.parse("nan@2,raise@4,stall@7:2.5,kill_ckpt@1")
    assert inj.pending() == ["nan@2", "raise@4", "stall@7:2.5",
                             "kill_ckpt@1"]


@pytest.mark.parametrize("spec", [
    "boom@2",          # unknown kind
    "nan",             # no @step
    "nan@x",           # non-integer step
    "nan@-1",          # negative step
    "kill_ckpt@0",     # save ordinals are 1-based
    "kill_ckpt@1:now",  # only :exit is a valid kill arg
    "stall@2:soon",    # stall arg must be float seconds
])
def test_fault_spec_rejects_bad_entries(spec):
    with pytest.raises(ValueError):
        FaultInjector.parse(spec)


def test_fault_spec_random_steps_are_seed_deterministic():
    a = FaultInjector.parse("nan@?,raise@?", seed=5, horizon=20)
    b = FaultInjector.parse("nan@?,raise@?", seed=5, horizon=20)
    c = FaultInjector.parse("nan@?,raise@?", seed=6, horizon=20)
    assert a.pending() == b.pending()
    assert a.pending() != c.pending()  # (astronomically unlikely to tie)


def test_env_var_spec_and_off_by_default(monkeypatch):
    monkeypatch.delenv("DML_FAULTS", raising=False)
    assert FaultInjector.from_flags(None) is None  # OFF is the default
    monkeypatch.setenv("DML_FAULTS", "nan@3")
    inj = FaultInjector.from_flags(None)
    assert inj is not None and inj.pending() == ["nan@3"]
    # An explicit spec wins over the env var.
    assert FaultInjector.from_flags("raise@1").pending() == ["raise@1"]


def _uint8_batches(n, start=0):
    r = np.random.default_rng(0)
    return [(r.integers(0, 256, (2, 8, 8, 3)).astype(np.uint8),
             r.integers(0, 10, 2).astype(np.int32)) for _ in range(start, n)]


def test_injector_nan_poisons_once_and_latches():
    inj = FaultInjector.parse("nan@1")
    out = list(inj.wrap_batches(_uint8_batches(3)))
    assert np.isnan(out[1][0]).all() and not np.isnan(
        out[0][0].astype(np.float32)).any()
    # A replay crossing the same index must NOT re-poison: the fault
    # fired and recovery is supposed to make progress past it.
    replay = list(inj.wrap_batches(_uint8_batches(3)))
    assert replay[1][0].dtype == np.uint8


def test_injector_raise_fires_at_absolute_index():
    inj = FaultInjector.parse("raise@5")
    events = FaultEvents()
    # start=4: the wrapper sees local index 1 == absolute index 5.
    it = inj.wrap_batches(iter(_uint8_batches(3)), events, start=4)
    next(it)
    with pytest.raises(InjectedFault):
        next(it)


def test_injector_refuses_to_poison_token_batches():
    inj = FaultInjector.parse("nan@0")
    tokens = (np.zeros((2, 8), np.int32), np.zeros((2, 8), np.int32))
    with pytest.raises(TypeError):
        next(inj.wrap_batches(iter([tokens])))


def test_mid_save_hook_kills_on_its_ordinal():
    inj = FaultInjector.parse("kill_ckpt@2")
    events = FaultEvents()
    hook = inj.mid_save_hook(events)
    hook()  # save #1: survives
    with pytest.raises(InjectedKill):
        hook()  # save #2: dies
    hook()  # fired-once: save #3 survives
    assert events.ckpt_kills == 1


# ---------------------------------------------------------------------------
# Retrying data path (data/retry.py)
# ---------------------------------------------------------------------------


def _flaky_factory(fail_at, times):
    """A seekable stream 0..5 whose batch ``fail_at`` raises its first
    ``times`` deliveries."""
    fails = {"left": times}

    def make(start):
        def gen():
            for i in range(start, 6):
                if i == fail_at and fails["left"] > 0:
                    fails["left"] -= 1
                    raise OSError(f"transient failure at {i}")
                yield i
        return gen()

    return make


def test_retry_recreates_the_source_at_the_failing_index():
    events = FaultEvents()
    got = list(retry_batches(
        _flaky_factory(3, times=1), RetryPolicy(backoff_s=0.0), events))
    assert got == [0, 1, 2, 3, 4, 5]  # nothing lost, nothing duplicated
    assert events.loader_retries == 1 and events.skipped_batches == 0


def test_retry_skips_a_persistently_bad_batch():
    events = FaultEvents()
    got = list(retry_batches(
        _flaky_factory(2, times=10),
        RetryPolicy(max_retries=5, max_attempts_per_batch=2, backoff_s=0.0),
        events,
    ))
    assert got == [0, 1, 3, 4, 5]  # batch 2 skipped, stream continues
    assert events.skipped_batches == 1 and events.loader_retries == 2


def test_retry_exhaustion_reraises():
    def always_dead(start):
        raise OSError("storage is gone")
        yield  # pragma: no cover

    with pytest.raises(OSError):
        list(retry_batches(always_dead, RetryPolicy(max_retries=2,
                                                    backoff_s=0.0)))


def test_retry_never_swallows_keyboard_interrupt():
    def interrupted(start):
        def gen():
            raise KeyboardInterrupt
            yield  # pragma: no cover
        return gen()

    with pytest.raises(KeyboardInterrupt):
        list(retry_batches(interrupted, RetryPolicy(max_retries=5,
                                                    backoff_s=0.0)))


def test_retry_policy_validates():
    with pytest.raises(ValueError):
        RetryPolicy(max_retries=-1)
    with pytest.raises(ValueError):
        RetryPolicy(max_attempts_per_batch=0)
    with pytest.raises(ValueError):
        RetryPolicy(backoff_mult=0.5)


class _FlakyDataset:
    """images/labels-style dataset whose array access fails N times."""

    def __init__(self, n=8, fail_times=0):
        r = np.random.default_rng(3)
        self._images = r.integers(0, 256, (n, 8, 8, 3)).astype(np.uint8)
        self.labels = r.integers(0, 10, n).astype(np.int32)
        self._fails = fail_times

    def __len__(self):
        return len(self.labels)

    @property
    def images(self):
        if self._fails > 0:
            self._fails -= 1
            raise OSError("transient dataset read")
        return self._images


def test_batch_loader_retry_recovers_a_transient_fault():
    from distributed_machine_learning_tpu.data.loader import BatchLoader

    loader = BatchLoader(_FlakyDataset(fail_times=1), batch_size=4,
                         retry=RetryPolicy(backoff_s=0.0))
    batches = list(loader)
    assert len(batches) == 2 and batches[0][0].shape == (4, 8, 8, 3)


@pytest.mark.parametrize("prefetch", [0, 2])
def test_batch_loader_surfaces_unrecovered_faults(prefetch):
    # Without the retry layer a producer death must RAISE in the
    # consumer, never leave the training loop blocked on an empty queue.
    from distributed_machine_learning_tpu.data.loader import BatchLoader

    loader = BatchLoader(_FlakyDataset(fail_times=99), batch_size=4,
                         prefetch=prefetch)
    with pytest.raises(OSError):
        list(loader)


# ---------------------------------------------------------------------------
# run_attempts (the supervisor's restart policy)
# ---------------------------------------------------------------------------


def test_run_attempts_retries_then_succeeds():
    events = FaultEvents()

    def attempt(i):
        if i < 2:
            raise RuntimeError(f"attempt {i} died")
        return "done"

    assert run_attempts(attempt, max_restarts=3, events=events) == "done"
    assert events.restarts == 2


def test_run_attempts_gives_up_loudly():
    def attempt(i):
        raise RuntimeError("always dead")

    with pytest.raises(RuntimeError):
        run_attempts(attempt, max_restarts=2)


def test_run_attempts_never_retries_keyboard_interrupt():
    calls = []

    def attempt(i):
        calls.append(i)
        raise KeyboardInterrupt

    with pytest.raises(KeyboardInterrupt):
        run_attempts(attempt, max_restarts=5)
    assert calls == [0]


# ---------------------------------------------------------------------------
# Non-finite-gradient guard (train/step.py) + resilience summary
# ---------------------------------------------------------------------------


def _cnn_batch(i, n=2):
    """Deterministic batch ``i`` of the chaos stream — cursor-keyed, so
    replays after a restart regenerate the identical arrays."""
    r = np.random.default_rng(1000 + i)
    return (r.integers(0, 256, (n, 32, 32, 3)).astype(np.uint8),
            r.integers(0, 10, n).astype(np.int32))


def _nan_batch(n=2):
    return (np.full((n, 32, 32, 3), np.nan, np.float32),
            np.zeros(n, np.int32))


@pytest.fixture(scope="module")
def guarded_cnn(tmp_path_factory):
    """A guarded VGGTest step with every signature the chaos run hits
    pre-compiled (uint8 fresh state, poisoned float32, restored state) —
    the tests use second-scale watchdog timeouts, and an XLA compile
    landing mid-run would read as a stall.  Real runs size the timeout
    in minutes, far above any compile."""
    import shutil

    from distributed_machine_learning_tpu.cli.common import (
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.models.vgg import VGGTest
    from distributed_machine_learning_tpu.train.checkpoint import (
        restore_checkpoint,
        save_checkpoint,
    )
    from distributed_machine_learning_tpu.train.step import make_train_step

    model = VGGTest(use_bn=False)
    step = make_train_step(model, augment=False, guard_nonfinite=True)
    step(init_model_and_state(model), *_cnn_batch(0))
    step(init_model_and_state(model), *_nan_batch())
    warm_dir = tmp_path_factory.mktemp("warm_ckpt")
    path = save_checkpoint(warm_dir, init_model_and_state(model))
    restored = restore_checkpoint(
        path, abstract_state=init_model_and_state(model)
    )
    step(restored, *_cnn_batch(0))
    shutil.rmtree(warm_dir, ignore_errors=True)
    return model, step


def test_guard_skips_the_update_and_preserves_state(guarded_cnn):
    from distributed_machine_learning_tpu.cli.common import (
        init_model_and_state,
    )

    model, step = guarded_cnn
    state = init_model_and_state(model)
    import jax
    params_before = jax.device_get(state.params)
    new_state, loss = step(state, *_nan_batch())
    assert int(jax.device_get(new_state.step)) == 0  # step NOT counted
    assert not np.isfinite(float(loss))  # the blowup is still observable
    for a, b in zip(jax.tree_util.tree_leaves(params_before),
                    jax.tree_util.tree_leaves(
                        jax.device_get(new_state.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # The next good batch trains normally from the preserved state.
    new_state, loss = step(new_state, *_cnn_batch(0))
    assert int(jax.device_get(new_state.step)) == 1
    assert np.isfinite(float(loss))


def test_unguarded_step_is_poisoned_by_the_same_batch():
    # The contrast case: guard off (the default — reference parity must
    # not mask numeric bugs) lets one NaN batch destroy the params.
    import jax

    from distributed_machine_learning_tpu.cli.common import (
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.models.vgg import VGGTest
    from distributed_machine_learning_tpu.train.step import make_train_step

    model = VGGTest(use_bn=False)
    step = make_train_step(model, augment=False)
    state, _ = step(init_model_and_state(model), *_nan_batch())
    assert int(jax.device_get(state.step)) == 1  # counted as if fine
    leaves = jax.tree_util.tree_leaves(jax.device_get(state.params))
    assert any(np.isnan(np.asarray(l)).any() for l in leaves)


def test_resilience_summary_renders_counters():
    from distributed_machine_learning_tpu.utils.summary import (
        resilience_summary,
    )

    events = FaultEvents()
    assert "clean run" in resilience_summary(events)
    events.skipped_steps = 2
    events.restarts = 1
    text = resilience_summary(events)
    assert "non-finite" in text and "restarts" in text
    assert "Total events" in text and "3" in text


# ---------------------------------------------------------------------------
# Dynamic loss scaling (train/lm_step.py)
# ---------------------------------------------------------------------------


def _tiny_lm():
    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )

    return TransformerLM(vocab_size=32, d_model=16, n_layers=1, n_heads=2)


def _lm_batch(rng=None):
    r = rng or np.random.default_rng(11)
    return (r.integers(0, 32, (2, 8)).astype(np.int32),
            r.integers(0, 32, (2, 8)).astype(np.int32))


@pytest.fixture(scope="module")
def scaled_lm_step():
    from distributed_machine_learning_tpu.train.lm_step import (
        make_lm_train_step,
    )

    model = _tiny_lm()
    return model, make_lm_train_step(model, dynamic_scale=True)


def test_dynamic_scale_doubles_after_growth_interval(scaled_lm_step):
    import jax

    from distributed_machine_learning_tpu.train.lm_step import (
        init_lm_state,
        with_dynamic_scale,
    )

    model, step = scaled_lm_step
    s = with_dynamic_scale(init_lm_state(model), init_scale=2.0**10,
                           growth_interval=2)
    toks, tgts = _lm_batch()
    s, loss = step(s, toks, tgts)
    assert float(s.loss_scale) == 2.0**10 and int(s.good_steps) == 1
    assert np.isfinite(float(loss))  # reported loss is UNSCALED
    s, _ = step(s, toks, tgts)
    assert float(s.loss_scale) == 2.0**11  # doubled after 2 good steps
    assert int(s.good_steps) == 0  # growth resets the streak
    assert int(jax.device_get(s.step)) == 2


def test_dynamic_scale_halves_and_skips_on_overflow(scaled_lm_step):
    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.train.lm_step import (
        init_lm_state,
        with_dynamic_scale,
    )

    model, step = scaled_lm_step
    inner = init_lm_state(model)
    # Poison one parameter leaf: the gradients are then non-finite, the
    # overflow path every bf16 run eventually hits.
    leaves, treedef = jax.tree_util.tree_flatten(inner.params)
    leaves[0] = jnp.full_like(leaves[0], jnp.nan)
    inner = inner.replace(params=jax.tree_util.tree_unflatten(treedef,
                                                              leaves))
    s = with_dynamic_scale(inner, init_scale=2.0**10, growth_interval=2)
    s2, loss = step(s, *_lm_batch())
    assert int(jax.device_get(s2.step)) == 0  # update skipped
    assert float(s2.loss_scale) == 2.0**9  # halved
    assert int(s2.good_steps) == 0
    assert not np.isfinite(float(loss))


def test_dynamic_scale_clamps_at_one(scaled_lm_step):
    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.train.lm_step import (
        init_lm_state,
        with_dynamic_scale,
    )

    model, step = scaled_lm_step
    inner = init_lm_state(model)
    leaves, treedef = jax.tree_util.tree_flatten(inner.params)
    leaves[0] = jnp.full_like(leaves[0], jnp.inf)
    inner = inner.replace(params=jax.tree_util.tree_unflatten(treedef,
                                                              leaves))
    s = with_dynamic_scale(inner, init_scale=1.0, growth_interval=2)
    s2, _ = step(s, *_lm_batch())
    assert float(s2.loss_scale) == 1.0  # never collapses below 1


def test_with_dynamic_scale_validates():
    from distributed_machine_learning_tpu.train.lm_step import (
        init_lm_state,
        with_dynamic_scale,
    )

    inner = init_lm_state(_tiny_lm())
    with pytest.raises(ValueError):
        with_dynamic_scale(inner, init_scale=0.5)
    with pytest.raises(ValueError):
        with_dynamic_scale(inner, growth_interval=0)


def test_scaler_events_are_counted_by_the_loop(scaled_lm_step):
    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.train.lm_step import (
        init_lm_state,
        with_dynamic_scale,
    )
    from distributed_machine_learning_tpu.train.loop import train_epoch

    model, step = scaled_lm_step
    inner = init_lm_state(model)
    leaves, treedef = jax.tree_util.tree_flatten(inner.params)
    leaves[0] = jnp.full_like(leaves[0], jnp.nan)
    inner = inner.replace(params=jax.tree_util.tree_unflatten(treedef,
                                                              leaves))
    s = with_dynamic_scale(inner, init_scale=2.0**10, growth_interval=2)
    events = FaultEvents()
    s, _ = train_epoch(step, s, [_lm_batch()], max_iters=1, events=events,
                       loss_print_every=10**9)
    assert events.skipped_steps == 1 and events.scaler_backoffs == 1


# ---------------------------------------------------------------------------
# The supervised run (runtime/supervisor.py::supervised_train)
# ---------------------------------------------------------------------------


def _make_batches(cursor):
    """Cursor-keyed batch factory over the deterministic chaos stream."""
    def gen():
        i = cursor
        while i < 64:
            yield _cnn_batch(i)
            i += 1
    return gen()


def _params_equal(a, b):
    import jax

    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree_util.tree_leaves(jax.device_get(a)),
                        jax.tree_util.tree_leaves(jax.device_get(b)))
    )


def test_supervised_fault_free_run_is_exact(guarded_cnn, tmp_path):
    import jax

    from distributed_machine_learning_tpu.cli.common import (
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.train.loop import train_epoch

    model, step = guarded_cnn
    events = FaultEvents()
    final = supervised_train(
        step, init_model_and_state(model), _make_batches,
        target_steps=5, ckpt_dir=tmp_path, save_every=2, events=events,
    )
    assert int(jax.device_get(final.step)) == 5
    assert events.total() == 0  # a clean run reports a clean bill
    plain = init_model_and_state(model)
    plain, _ = train_epoch(step, plain, [_cnn_batch(i) for i in range(5)],
                           max_iters=10**9, loss_print_every=10**9)
    assert _params_equal(final.params, plain.params)


@pytest.mark.faultinject
def test_chaos_run_matches_fault_free_run(guarded_cnn, tmp_path):
    """The acceptance keystone: all four fault classes in ONE supervised
    run — kill during the first save, NaN gradient at batch 4, loader
    raise at batch 6, stall past the watchdog at batch 8 — and the run
    still finishes at the target step count with bit-identical params to
    the fault-free trajectory over the same stream minus the one
    guard-skipped batch, with every fault class visible in the
    counters."""
    import jax

    from distributed_machine_learning_tpu.cli.common import (
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.train.checkpoint import (
        latest_checkpoint,
    )
    from distributed_machine_learning_tpu.train.loop import train_epoch

    model, step = guarded_cnn
    events = FaultEvents()
    injector = FaultInjector.parse("kill_ckpt@1,nan@4,raise@6,stall@8:4.0")
    final = supervised_train(
        step, init_model_and_state(model), _make_batches,
        target_steps=10, ckpt_dir=tmp_path, save_every=3, max_restarts=4,
        events=events, watchdog_timeout=1.5, injector=injector,
        retry=RetryPolicy(max_retries=3), keep_last_n=2,
    )
    assert int(jax.device_get(final.step)) == 10

    # Every injected fault class is observable in the counters.
    assert events.ckpt_kills == 1     # kill_ckpt@1
    assert events.skipped_steps == 1  # nan@4
    assert events.loader_retries >= 1  # raise@6
    assert events.stalls >= 1         # stall@8
    assert events.restarts >= 2       # the kill and the stall both restart

    # Bit-identical to the fault-free run of the same seed, minus the
    # guard-skipped batch (index 4 was consumed but its update skipped).
    clean = init_model_and_state(model)
    applied = [_cnn_batch(i) for i in range(11) if i != 4]
    clean, _ = train_epoch(step, clean, applied, max_iters=10**9,
                           loss_print_every=10**9)
    assert _params_equal(final.params, clean.params)

    # keep_last_n GC ran and the newest complete checkpoint survived.
    latest = latest_checkpoint(tmp_path)
    assert latest is not None and latest.endswith("step_10")
    complete = [d for d in os.listdir(tmp_path)
                if os.path.exists(os.path.join(tmp_path, d,
                                               "sgd_config.json"))]
    assert len(complete) <= 2


@pytest.mark.faultinject
def test_supervised_preemption_checkpoints_and_resumes(guarded_cnn,
                                                       tmp_path):
    import jax

    from distributed_machine_learning_tpu.cli.common import (
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.train.loop import train_epoch

    model, step = guarded_cnn
    events = FaultEvents()
    polls = {"n": 0}

    def stop():  # "preemption" arrives after the first save boundary
        polls["n"] += 1
        return polls["n"] > 3

    partial = supervised_train(
        step, init_model_and_state(model), _make_batches,
        target_steps=8, ckpt_dir=tmp_path, save_every=3, events=events,
        stop=stop,
    )
    stopped_at = int(jax.device_get(partial.step))
    assert 0 < stopped_at < 8
    assert events.preemptions == 1

    # A fresh supervised run auto-resumes from the preemption checkpoint
    # and lands exactly where an uninterrupted run would have.
    final = supervised_train(
        step, init_model_and_state(model), _make_batches,
        target_steps=8, ckpt_dir=tmp_path, save_every=3,
    )
    assert int(jax.device_get(final.step)) == 8
    clean = init_model_and_state(model)
    clean, _ = train_epoch(step, clean, [_cnn_batch(i) for i in range(8)],
                           max_iters=10**9, loss_print_every=10**9)
    assert _params_equal(final.params, clean.params)


def test_supervised_train_validates():
    with pytest.raises(ValueError):
        supervised_train(None, None, _make_batches, target_steps=0,
                         ckpt_dir="/tmp/x")
    with pytest.raises(ValueError):
        supervised_train(None, None, _make_batches, target_steps=1,
                         ckpt_dir="/tmp/x", save_every=0)
    with pytest.raises(ValueError):
        run_attempts(lambda i: None, max_restarts=-1)


# ---------------------------------------------------------------------------
# CLI wiring (--resume auto, --faults, --guard-nonfinite, ...)
# ---------------------------------------------------------------------------


def test_cli_flags_validate():
    from distributed_machine_learning_tpu.cli.common import (
        make_flag_parser,
        parse_flags,
    )

    parser = make_flag_parser("test")
    assert parse_flags(parser, []).resume is None
    base = ["--ckpt-dir", "/tmp/x"]
    assert parse_flags(parser, base + ["--resume"]).resume == "latest"
    assert parse_flags(parser, base + ["--resume", "auto"]).resume == "auto"
    for bad in (
        ["--resume"],          # any resume mode requires --ckpt-dir
        ["--resume", "auto"],  # auto requires --ckpt-dir
        base + ["--resume", "auto", "--max-restarts", "-1"],
        ["--keep-last-n", "0"],
        ["--loader-retries", "-2"],
        ["--faults", "boom@3"],  # spec validated at parse time
    ):
        with pytest.raises(SystemExit):
            parse_flags(parser, bad)


@pytest.mark.faultinject
def test_part_cli_supervised_chaos_run(tmp_path, capsys):
    """The CNN CLI end to end under --resume auto with injected faults:
    a NaN batch (skipped by the guard), a loader raise (retried), and a
    kill during the first checkpoint save (restarted) — the run must
    finish, leave a complete checkpoint, and print every recovery in the
    resilience summary."""
    from distributed_machine_learning_tpu.cli import part1
    from distributed_machine_learning_tpu.train.checkpoint import (
        checkpoint_cursor,
        latest_checkpoint,
    )

    ck = tmp_path / "ck"
    part1.main([
        "--batch-size", "4", "--max-iters", "3", "--epochs", "2",
        "--model", "vggtest", "--eval-batches", "0",
        "--data-root", str(tmp_path), "--ckpt-dir", str(ck),
        "--resume", "auto", "--max-restarts", "2", "--keep-last-n", "1",
        "--guard-nonfinite", "--loader-retries", "2",
        "--faults", "kill_ckpt@1,nan@2,raise@4",
    ])
    out = capsys.readouterr().out
    assert "Resilience summary" in out
    assert "updates skipped (non-finite grads)" in out
    assert "injected mid-checkpoint kills" in out
    assert "supervisor restarts" in out
    assert "data-loader retries" in out
    latest = latest_checkpoint(ck)
    # 2 epochs x 3 batches, one skipped on the first (pre-kill) attempt
    # whose epoch was replayed clean after the restart: 6 applied steps.
    assert latest is not None and latest.endswith("step_6")
    assert checkpoint_cursor(latest) is None  # epoch-cycle saves: no cursor
    # keep_last_n=1: only the newest complete checkpoint remains.
    steps = [d for d in os.listdir(ck) if d.startswith("step_")]
    assert steps == ["step_6"]
