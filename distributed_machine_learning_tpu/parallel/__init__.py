from distributed_machine_learning_tpu.parallel.strategies import (
    SyncStrategy,
    NoSync,
    AllReduce,
    GatherScatter,
    RingAllReduce,
    get_strategy,
    STRATEGIES,
)

from distributed_machine_learning_tpu.parallel.fsdp import (
    FSDPState,
    make_fsdp_train_step,
    shard_fsdp_state,
    gather_fsdp_params,
)

__all__ = [
    "SyncStrategy",
    "NoSync",
    "AllReduce",
    "GatherScatter",
    "RingAllReduce",
    "get_strategy",
    "STRATEGIES",
    "FSDPState",
    "make_fsdp_train_step",
    "shard_fsdp_state",
    "gather_fsdp_params",
]
