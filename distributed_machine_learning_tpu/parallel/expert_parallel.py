"""Expert parallelism for the MoE transformer — GSPMD sharding rules.

Same design as ``parallel/tensor_parallel.py``: declare where params live,
jit the unmodified step with those shardings, and let XLA's partitioner
derive the comm.  Expert-owned params (leading ``[n_experts, ...]`` axis:
``w_in``/``b_in``/``w_out``/``b_out`` of every ``MoEMLP``) shard that axis
over the mesh's ``expert`` axis; the dispatch/combine einsums in
``models/moe.py`` then lower to the token all-to-all over ICI.  Everything
else (attention, norms, router, embeddings) stays replicated; the batch
shards over ``data_axis``, giving EP×DP on one mesh.
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_machine_learning_tpu.models.moe import (
    SEQ_LOCAL_ATTN_IMPLS,
    MoETransformerLM,
)
from distributed_machine_learning_tpu.parallel.gspmd import (
    make_cached_sharded_step,
    shard_state,
    state_shardings,
)
from distributed_machine_learning_tpu.train.losses import lm_cross_entropy
from distributed_machine_learning_tpu.train.optimizers import update_fn_for_config
from distributed_machine_learning_tpu.train.state import TrainState

EXPERT_AXIS = "expert"
_EXPERT_PARAMS = {"w_in", "b_in", "w_out", "b_out"}


def ep_spec_for(path: tuple[str, ...], ndim: int, expert_axis: str = EXPERT_AXIS) -> P:
    """Expert-owned leaves shard their leading axis; the rest replicate."""
    if path and path[-1] in _EXPERT_PARAMS and "moe" in path:
        return P(expert_axis, *(None,) * (ndim - 1))
    return P(*(None,) * ndim)


def _spec_for(expert_axis: str):
    # gspmd.SpecFor passes the leaf shape; the EP rule only needs rank.
    return lambda path, shape: ep_spec_for(path, len(shape), expert_axis)


def ep_state_shardings(state: TrainState, mesh: Mesh, expert_axis: str = EXPERT_AXIS):
    return state_shardings(state, mesh, _spec_for(expert_axis))


def shard_ep_state(
    state: TrainState, mesh: Mesh, expert_axis: str = EXPERT_AXIS
) -> TrainState:
    return shard_state(state, mesh, _spec_for(expert_axis))


def _moe_step_impl(model: MoETransformerLM, state: TrainState, tokens, targets):
    def loss_fn(params):
        logits, mutated = model.apply(
            {"params": params}, tokens, train=True, mutable=["losses"]
        )
        ce = lm_cross_entropy(logits, targets)
        aux_leaves = jax.tree_util.tree_leaves(mutated.get("losses", {}))
        aux = sum(jax.numpy.sum(a) for a in aux_leaves) if aux_leaves else 0.0
        return ce + model.aux_loss_weight * aux, ce

    (loss, ce), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
    new_params, new_momentum = update_fn_for_config(state.config)(
        state.params, state.momentum, grads, state.config, step=state.step
    )
    new_state = state.replace(
        params=new_params, momentum=new_momentum, step=state.step + 1
    )
    return new_state, ce


def init_moe_state(model: MoETransformerLM, seed: int = 69143,
                   config=None) -> TrainState:
    """``config``: optional optimizer config (as in ``init_lm_state``);
    the EP step dispatches its update from the state's config type."""
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state

    return init_lm_state(model, seed=seed, config=config)


def make_ep_train_step(
    model: MoETransformerLM,
    mesh: Mesh | None = None,
    data_axis: str = "batch",
    expert_axis: str = EXPERT_AXIS,
):
    """Build the EP(+DP) MoE train step: ``step(state, tokens, targets) →
    (state, ce_loss)``.  Without a mesh: plain jit (the single-device
    reference).  With a mesh: state placed via ``shard_ep_state``,
    tokens/targets sharded over ``data_axis`` (``shard_tp_batch`` works)."""
    if model.attn_impl not in SEQ_LOCAL_ATTN_IMPLS:
        raise ValueError(
            "expert-parallel step requires a sequence-LOCAL attention "
            "(dense/flash/auto): the sequence is not sharded here, so the "
            "ring/ulysses impls have no axis to run over"
        )
    if mesh is None:
        return jax.jit(partial(_moe_step_impl, model), donate_argnums=(0,))
    if model.moe_impl != "einsum":
        # ragged_dot has no GSPMD partitioning rule that would recover the
        # token all-to-all from an expert-sharded leading axis; only the
        # one-hot einsum form shards over the expert axis.  The grouped
        # path stays single-device / shard_map-DP (ops/grouped.py).
        raise ValueError(
            "the expert-sharded GSPMD step requires moe_impl='einsum' "
            f"(got {model.moe_impl!r}): the dispatch/combine einsums are "
            "what XLA partitions into the all-to-all; the grouped "
            "ragged_dot path does not shard over the expert axis"
        )
    if model.attn_impl in ("flash", "auto") and model.flash_mesh is None:
        # A bare Pallas (Mosaic) custom call inside this GSPMD-
        # partitioned jit has no sharding rules, so flash runs through
        # the model's fully-manual shard_map wrap (batch dim sharded)
        # instead (models/transformer.py::Attention.flash_mesh): the
        # kernel sees local per-device shapes and never meets the
        # partitioner — valid on CPU interpret AND real TPU meshes.
        model = model.clone(flash_mesh=mesh, flash_batch_axis=data_axis)
    impl = partial(_moe_step_impl, model)
    for a in (data_axis, expert_axis):
        if a not in mesh.axis_names:
            raise ValueError(f"mesh is missing axis {a!r}: {mesh.axis_names}")
    if model.n_experts % mesh.shape[expert_axis]:
        raise ValueError(
            f"n_experts={model.n_experts} must be divisible by the "
            f"expert-axis size {mesh.shape[expert_axis]}"
        )
    batch_sharding = NamedSharding(mesh, P(data_axis, None))
    return make_cached_sharded_step(impl, mesh, _spec_for(expert_axis), batch_sharding)
