"""ResNet family: shapes, parameter counts, registry, train-step integration.

BASELINE.json names ResNet-18/CIFAR-10 as the headline config (and
ResNet-50 as stretch) even though the reference code is VGG-11 — see
SURVEY.md §0.1.  Both families are first-class here.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.models.registry import get_model, list_models
from distributed_machine_learning_tpu.models.resnet import ResNet18, ResNet50


def _param_count(params):
    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


def test_resnet18_cifar_shapes_and_params():
    model = ResNet18()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((2, 32, 32, 3)),
                           train=False)
    logits = model.apply(variables, jnp.zeros((2, 32, 32, 3)), train=False)
    assert logits.shape == (2, 10)
    # torchvision ResNet-18 has ~11.7M params; the CIFAR stem (3×3 vs 7×7)
    # shaves ~8k — expect ~11.2M with the 10-class head.
    n = _param_count(variables["params"])
    assert 10_500_000 < n < 11_500_000, n
    assert "batch_stats" in variables


@pytest.mark.slow
def test_resnet50_imagenet_stem():
    model = ResNet50(cifar_stem=False, num_classes=1000)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)),
                           train=False)
    # torchvision ResNet-50: ~25.6M params.
    n = _param_count(variables["params"])
    assert 23_000_000 < n < 26_500_000, n
    out = model.apply(variables, jnp.zeros((1, 64, 64, 3)), train=False)
    assert out.shape == (1, 1000)


def test_resnet_train_mutates_batch_stats():
    model = ResNet18()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                           train=False)
    logits, mutated = model.apply(
        variables, jnp.ones((4, 32, 32, 3)), train=True, mutable=["batch_stats"]
    )
    assert logits.shape == (4, 10)
    old = jax.tree_util.tree_leaves(variables["batch_stats"])
    new = jax.tree_util.tree_leaves(mutated["batch_stats"])
    assert any(not np.allclose(o, n) for o, n in zip(old, new))


def test_registry_covers_both_families():
    names = list_models()
    for expected in ("vgg11", "vgg19", "resnet18", "resnet50"):
        assert expected in names
    m = get_model("resnet18", compute_dtype=jnp.bfloat16)
    variables = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                       train=False)
    assert all(p.dtype == jnp.float32
               for p in jax.tree_util.tree_leaves(variables["params"]))
    assert m.apply(variables, jnp.zeros((1, 32, 32, 3)),
                   train=False).dtype == jnp.float32
    with pytest.raises(ValueError):
        get_model("alexnet")


@pytest.mark.slow
def test_resnet18_distributed_train_step(mesh8):
    """ResNet-18 through the full part3 path on the 8-device mesh: ring
    all-reduce, axis-synced BN, SGD — the BASELINE.json headline config.

    Slow-marked as a full-size-model duplicate (pytest.ini policy): the
    ResNet-18 model itself and the distributed part3 step are each
    covered by cheaper default-run tests; this 15s compile composes
    them at full size."""
    from distributed_machine_learning_tpu.cli.common import init_model_and_state
    from distributed_machine_learning_tpu.parallel.strategies import get_strategy
    from distributed_machine_learning_tpu.train.step import (
        make_train_step,
        shard_batch,
    )

    model = ResNet18()
    state = init_model_and_state(model)
    step = make_train_step(model, get_strategy("ring", bucket_bytes=1 << 20),
                           mesh=mesh8)
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (16, 32, 32, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, 16).astype(np.int32)
    x, y = shard_batch(mesh8, images, labels)
    state, loss = step(state, x, y)
    assert np.isfinite(float(loss))
    assert int(state.step) == 1
