"""1F1B pipeline schedule — hand-written backward, O(P) activation memory.

The GPipe step (``parallel/pipeline.py``) runs ALL forwards then all
backwards: ``jax.grad`` of the forward scan fixes that schedule, and a
stage must keep every microbatch's span activations (or remat them)
until the backward sweep returns — activation memory O(M).  1F1B
(PipeDream-flush — the schedule production pipelines actually use)
interleaves: after a short warmup, every tick each stage runs ONE
backward then ONE forward, so a stage never holds more than ~P
in-flight microbatches regardless of M.  The bubble fraction is the
same (P−1)/(M+P−1) as GPipe — 1F1B's win is MEMORY, which is what lets
M grow large enough to make the bubble small.

``jax.grad`` cannot express an interleaved schedule, so this module
writes the backward by hand:

- **Warmup** (P−1 ticks): forward-only GPipe ticks.  Stage s forwards
  microbatches 0..P−2−s, storing each SPAN INPUT in a ring buffer.
- **Steady** (M+P−1 ticks): each tick, stage s
  1. *forwards* microbatch f = u+P−1−s (stage 0 embeds + injects;
     masked once f ≥ M), stores its input, ppermutes the output
     downstream;
  2. *backwards* microbatch b = u−(P−1−s) (masked until b ≥ 0):
     recomputes its span from the stored input under ``jax.vjp`` —
     the recompute-from-input memory profile remat gives GPipe, but
     scheduled per-microbatch — seeds the cotangent from the loss head
     on the last stage or from the downstream-arrived cotangent
     elsewhere, accumulates local param grads, and ppermutes the input
     cotangent upstream.  Stage 0 routes its input cotangent into the
     embedding gradient instead.

Both sub-ticks live in ONE ``lax.scan`` body (masked on the tick
index), so program size is independent of M and P — the same
trace-once discipline as the GPipe loop.  The ring buffer holds 2P
microbatch inputs: in-flight ids at a stage span at most 2(P−1)−2s+1,
so id mod 2P never collides (P slots would collide for P=2 and odd P).

The single vjp per tick covers every stage uniformly: it differentiates
``(blocks, ln_f, lm_head, act) → (span_out, head_loss(span_out))`` and
seeds ``(g_y, g_loss)`` — last stage ``(0, valid/M)``, others
``(g_from_downstream, 0)`` — so boundary-module grads fall out masked
without a second transpose.

Update-equivalence to the GPipe step (same grads, same loss, any M, P)
is property-tested in ``tests/test_pipeline_1f1b.py``; the state
layout, flags, and helpers are shared with ``parallel/pipeline.py``
(``init_pipeline_state`` / ``shard_pp_state`` / ``microbatch``).
Beyond-parity capability: the reference has no pipeline parallelism at
all (SURVEY.md §2.3).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.parallel.pipeline import (
    PIPE_AXIS,
    _apply_local_span,
    _block_module,
    _whole_layer_remat,
)
from distributed_machine_learning_tpu.train.losses import lm_cross_entropy
from distributed_machine_learning_tpu.train.optimizers import update_fn_for_config
from distributed_machine_learning_tpu.train.state import TrainState


def _1f1b_loss_and_grads(
    model: TransformerLM,
    params: dict,
    tokens_mb,  # [M, mb, L] int32 (replicated)
    targets_mb,  # [M, mb, L] int32
    *,
    pipe_axis: str,
    num_stages: int,
):
    """(mean loss, grads pytree) via the hand-scheduled 1F1B pipeline."""
    import flax.linen as nn

    block = _block_module(model)
    M, mb, L = tokens_mb.shape
    E = model.d_model
    S = num_stages
    rank = lax.axis_index(pipe_axis)
    positions = jnp.arange(L)
    is_first = rank == 0
    is_last = rank == S - 1
    perm_fwd = [(i, (i + 1) % S) for i in range(S)]
    perm_bwd = [(i, (i - 1) % S) for i in range(S)]
    BUF = 2 * S  # ring-buffer slots (see module docstring)

    embed_mod = nn.Embed(model.vocab_size, E, dtype=model.compute_dtype)
    ln_f_mod = nn.LayerNorm(dtype=model.compute_dtype)
    head_mod = nn.Dense(model.vocab_size, dtype=model.compute_dtype)

    def embed_apply(embed_params, tok):
        return embed_mod.apply({"params": embed_params}, tok)

    def span_and_loss(blocks_p, ln_f_p, head_p, act, tgt):
        """The uniform per-stage differentiated region: span forward plus
        the loss head on its output.  Cotangent seeding picks which of
        the two outputs actually drives the backward on this stage.
        ``model.remat`` checkpoints each layer inside the vjp, so the
        recompute holds one layer's activations at a time (the same
        knob the GPipe step honors)."""
        y = _apply_local_span(block, blocks_p, act, positions,
                              remat=_whole_layer_remat(model))
        h = ln_f_mod.apply({"params": ln_f_p}, y)
        logits = head_mod.apply({"params": head_p}, h)
        loss = lm_cross_entropy(logits.astype(jnp.float32), tgt)
        return y, loss

    def fwd_sub_tick(act_in, act_buf, f_id):
        """Forward microbatch ``f_id`` (traced; masked by validity):
        inject on stage 0, store the span input, return the span output
        for the downstream permute and the updated buffer."""
        f_valid = (f_id >= 0) & (f_id < M)
        tok = lax.dynamic_index_in_dim(
            tokens_mb, jnp.clip(f_id, 0, M - 1), keepdims=False
        )
        x = jnp.where(is_first & f_valid, embed_apply(params["embed"], tok),
                      act_in)
        act_buf = lax.dynamic_update_index_in_dim(
            act_buf, x, f_id % BUF, axis=0
        )
        y = _apply_local_span(block, params["blocks"], x, positions)
        return y, act_buf

    def bwd_sub_tick(g_in, act_buf, b_id, grads, loss_acc):
        """Backward microbatch ``b_id``: recompute the span from its
        stored input under vjp, seed (g_y, g_loss), accumulate local
        grads, return the upstream cotangent."""
        b_valid = ((b_id >= 0) & (b_id < M))
        bf = b_valid.astype(jnp.float32)
        act = lax.dynamic_index_in_dim(act_buf, b_id % BUF, axis=0,
                                       keepdims=False)
        tgt = lax.dynamic_index_in_dim(
            targets_mb, jnp.clip(b_id, 0, M - 1), keepdims=False
        )
        (y, loss), vjp = jax.vjp(
            span_and_loss, params["blocks"], params["ln_f"],
            params["lm_head"], act, tgt,
        )
        g_y = jnp.where(is_last | ~b_valid, jnp.zeros_like(y), g_in)
        g_loss = jnp.where(is_last & b_valid, 1.0 / M, 0.0)
        g_blocks, g_lnf, g_head, g_act, _ = vjp(
            (g_y.astype(y.dtype), g_loss)
        )
        # Stage 0's input cotangent belongs to the embedding, not the
        # ring: route it (masked) through the embed vjp — a scatter-add.
        # The raw g_act still rides the wrap-around hop to the last
        # stage, which discards it (``is_last`` seeds from the loss
        # cotangent instead), so no extra masking is needed on the wire.
        tok_b = lax.dynamic_index_in_dim(
            tokens_mb, jnp.clip(b_id, 0, M - 1), keepdims=False
        )
        _, embed_vjp = jax.vjp(
            lambda ep: embed_apply(ep, tok_b), params["embed"]
        )
        (g_embed,) = embed_vjp(
            jnp.where(is_first & b_valid, g_act, jnp.zeros_like(g_act))
        )
        grads = {
            "embed": jax.tree_util.tree_map(
                lambda a, g: a + g, grads["embed"], g_embed
            ),
            "blocks": jax.tree_util.tree_map(
                lambda a, g: a + bf * g, grads["blocks"], g_blocks
            ),
            "ln_f": jax.tree_util.tree_map(
                lambda a, g: a + bf * g, grads["ln_f"], g_lnf
            ),
            "lm_head": jax.tree_util.tree_map(
                lambda a, g: a + bf * g, grads["lm_head"], g_head
            ),
        }
        loss_acc = loss_acc + jnp.where(is_last & b_valid, loss, 0.0)
        return g_act, grads, loss_acc

    # --- Warmup: P−1 forward-only GPipe ticks (stage s sees mb t−s). ---
    act0 = jnp.zeros((mb, L, E), model.compute_dtype)
    act_buf0 = jnp.zeros((BUF, mb, L, E), model.compute_dtype)

    def warmup_tick(carry, t):
        act_in, act_buf = carry
        y, act_buf = fwd_sub_tick(act_in, act_buf, t - rank)
        return (lax.ppermute(y, pipe_axis, perm_fwd), act_buf), None

    (act_in, act_buf), _ = lax.scan(
        warmup_tick, (act0, act_buf0), jnp.arange(S - 1)
    )

    # --- Steady: M+P−1 ticks of one forward + one backward each. ---
    grads0 = {
        "embed": jax.tree_util.tree_map(jnp.zeros_like, params["embed"]),
        "blocks": jax.tree_util.tree_map(jnp.zeros_like, params["blocks"]),
        "ln_f": jax.tree_util.tree_map(jnp.zeros_like, params["ln_f"]),
        "lm_head": jax.tree_util.tree_map(jnp.zeros_like, params["lm_head"]),
    }

    def steady_tick(carry, u):
        act_in, g_in, act_buf, grads, loss_acc = carry
        # Forward first: on the last stage, microbatch u is forwarded
        # and backwarded in the SAME tick, so its input must be stored
        # before the backward reads it.
        y, act_buf = fwd_sub_tick(act_in, act_buf, u + (S - 1) - rank)
        g_act, grads, loss_acc = bwd_sub_tick(
            g_in, act_buf, u - (S - 1) + rank, grads, loss_acc
        )
        return (
            lax.ppermute(y, pipe_axis, perm_fwd),
            lax.ppermute(g_act, pipe_axis, perm_bwd),
            act_buf,
            grads,
            loss_acc,
        ), None

    g0 = jnp.zeros((mb, L, E), model.compute_dtype)
    (_, _, _, grads, loss_acc), _ = lax.scan(
        steady_tick,
        (act_in, g0, act_buf, grads0, jnp.zeros((), jnp.float32)),
        jnp.arange(M + S - 1),
    )
    return loss_acc / M, grads


def _pp1f1b_step_impl(
    model, state: TrainState, tokens_mb, targets_mb, *, pipe_axis, num_stages
):
    from distributed_machine_learning_tpu.parallel.pipeline import (
        _reject_lars,
    )

    _reject_lars(state.config)
    loss, grads = _1f1b_loss_and_grads(
        model, state.params, tokens_mb, targets_mb,
        pipe_axis=pipe_axis, num_stages=num_stages,
    )
    loss = lax.psum(loss, pipe_axis)
    # Boundary-module grads are non-zero on one stage each — share them
    # (identical to the GPipe step's reduction).
    for name in ("embed", "ln_f", "lm_head"):
        grads[name] = jax.tree_util.tree_map(
            lambda g: lax.psum(g, pipe_axis), grads[name]
        )
    new_params, new_momentum = update_fn_for_config(state.config)(
        state.params, state.momentum, grads, state.config, step=state.step
    )
    new_state = state.replace(
        params=new_params, momentum=new_momentum, step=state.step + 1
    )
    return new_state, loss


def make_pp_1f1b_lm_train_step(
    model: TransformerLM,
    mesh: Mesh,
    num_microbatches: int,
    pipe_axis: str = PIPE_AXIS,
):
    """Build the 1F1B ``step(state, tokens_mb, targets_mb)`` — drop-in
    for ``make_pp_lm_train_step`` (same state layout, same input
    layout, update-equivalent; O(P) activation memory instead of O(M)).
    """
    from distributed_machine_learning_tpu.parallel.pipeline import (
        make_pipeline_step,
    )

    return make_pipeline_step(
        _pp1f1b_step_impl, model, mesh, num_microbatches, pipe_axis
    )
