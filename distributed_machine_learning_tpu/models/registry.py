"""Model registry: name → constructor, shared by the CLI and bench.

The reference exposes exactly one model factory (`VGG11()` at
`part1/model.py:49-50`); its cfg table lists VGG11/13/16/19
(`part1/model.py:3-8`) and BASELINE.json's configs name ResNet-18 (with
ResNet-50 as the scale-out stretch).  All of those are registered here.

`use_bn` semantics: VGG takes it literally (off = part1/2a/2b parity, on
= part3 parity — `part3/model.py:24`); ResNets are BN-architectures, so
they accept and ignore it (BN always on).
"""

from __future__ import annotations

from typing import Any

from distributed_machine_learning_tpu.models import resnet, vgg

# Derived from each family's cfg table — one source of truth; a variant
# added to a model module's _cfg is immediately available here.
_VGG_NAMES = {k.lower(): k for k in vgg._cfg}
_RESNET_NAMES = {k.lower(): k for k in resnet._cfg}


def list_models() -> list[str]:
    return sorted(_VGG_NAMES) + sorted(_RESNET_NAMES)


def get_model(name: str, *, use_bn: bool = False, compute_dtype: Any = None,
              num_classes: int = 10, cifar_stem: bool = True):
    """Build a model by lowercase name (e.g. "vgg11", "resnet18")."""
    key = name.lower()
    kw: dict[str, Any] = {"num_classes": num_classes}
    if compute_dtype is not None:
        kw["compute_dtype"] = compute_dtype
    if key in _VGG_NAMES:
        return vgg.VGG(name_cfg=_VGG_NAMES[key], use_bn=use_bn, **kw)
    if key in _RESNET_NAMES:
        return resnet.ResNet(name_cfg=_RESNET_NAMES[key],
                             cifar_stem=cifar_stem, **kw)
    raise ValueError(f"unknown model {name!r}; available: {list_models()}")
