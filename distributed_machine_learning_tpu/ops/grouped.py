"""Sort-based grouped expert MLP — the dropless MoE compute path.

The einsum dispatch in ``models/moe.py`` is the right shape for GSPMD
expert parallelism (the one-hot dispatch/combine einsums are what the
partitioner turns into the token all-to-all), but on a single device it
pays O(N·E·C·D) = O(1.25·N²·D) FLOPs of pure data movement per
dispatch/combine pair — quadratic in tokens and all of it off the MXU's
useful-work path.  The grouped path here is the TPU-idiomatic
alternative (the design MegaBlocks argues for on GPUs, mapped onto
XLA's native ragged matmul): sort token rows by their routed expert,
run one ``lax.ragged_dot`` per projection over the contiguous groups,
and unsort.  Dispatch cost falls to O(N·D) gather/scatter bandwidth,
and the expert matmuls run at dense-matmul MFU (measured on this
repo's chip: 134 TF/s ragged vs 94 TF/s effective for the einsum
fragment at N=8k, D=2k, F=8k — before counting the combine einsum).

It is also **dropless**: every token reaches its expert, with no
capacity rounding — group sizes are data-dependent *values*, which
``ragged_dot`` consumes without shape dynamism (output shape stays
[N, F]).  Capacity/overflow semantics (Switch's) remain available via
the einsum path; parity between the two holds whenever capacity is
ample enough that nothing drops (tested).

Scope: single-device, shard_map-style data parallelism (each device
runs this on its local tokens), and — via
:func:`grouped_expert_mlp_ep` — real expert parallelism under a
fully-manual shard_map: token rows travel to their expert's owner
device through an explicit ``lax.all_to_all`` along the expert mesh
axis, ``ragged_dot`` runs over the received groups locally, and the
outputs ride the inverse all-to-all home.  ``ragged_dot`` has no GSPMD
partitioning rule, so the automatic-partitioner EP step keeps the
einsum path (guarded in ``parallel/expert_parallel.py``); the manual
path here is how the dropless kernel composes with EP.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def sort_by_expert(expert_idx: jax.Array, n_experts: int):
    """Permutation that groups token rows by expert, plus group sizes.

    Returns ``(order, inv_order, group_sizes)``: ``order`` sorts rows so
    expert 0's tokens come first, ``inv_order`` undoes it, and
    ``group_sizes[e]`` counts expert e's tokens (int32, as
    ``lax.ragged_dot`` requires).

    Counting sort, not ``argsort``: a bitonic sort of N int keys costs
    ~log²N full-array passes on the VPU (measured ~2 ms at N=8k on this
    chip — comparable to one of the expert matmuls it feeds).  With E
    experts the permutation is cheaper to *construct*: one [N, E] cumsum
    over the routing one-hot gives each token its rank within its
    expert's group, an exclusive-sum of group sizes gives each group's
    base offset, and rank + offset IS the token's destination slot —
    stable, total, and O(N·E) elementwise work.
    """
    n = expert_idx.shape[0]
    onehot = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.int32)  # [N, E]
    ranks = jnp.cumsum(onehot, axis=0)  # rank-within-expert, 1-based at own row
    group_sizes = ranks[-1]  # [E] — totals; int32 already
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(group_sizes)[:-1]]
    )  # exclusive prefix: group e starts at offsets[e]
    # Destination slot of each token = its group's base + its 0-based rank.
    dest = offsets[expert_idx] + (
        jnp.sum(ranks * onehot, axis=1, dtype=jnp.int32) - 1
    )
    inv_order = dest  # sorted[dest[i]] = tokens[i]  ⇒  dest inverts order
    order = jnp.zeros((n,), jnp.int32).at[dest].set(
        jnp.arange(n, dtype=jnp.int32)
    )
    return order, inv_order, group_sizes


@jax.custom_vjp
def _permute_rows(x: jax.Array, perm: jax.Array, inv_perm: jax.Array):
    """``x[perm]`` with a permutation-aware VJP.

    ``jnp.take``'s generic transpose is a scatter-add (indices could
    repeat), which TPUs execute row-at-a-time — profiled at ~22 GB/s on
    this chip, ~3 ms per [8k, 2k] un-permute in the MoE backward.  A
    permutation is bijective, so its cotangent is just the gather by the
    inverse permutation: both directions run at gather (HBM) speed.
    """
    return jnp.take(x, perm, axis=0)


def _permute_rows_fwd(x, perm, inv_perm):
    return jnp.take(x, perm, axis=0), (perm, inv_perm)


def _permute_rows_bwd(res, ct):
    perm, inv_perm = res
    return jnp.take(ct, inv_perm, axis=0), None, None


_permute_rows.defvjp(_permute_rows_fwd, _permute_rows_bwd)


def grouped_expert_mlp(
    tokens: jax.Array,
    expert_idx: jax.Array,
    w_in: jax.Array,
    b_in: jax.Array,
    w_out: jax.Array,
    b_out: jax.Array,
    *,
    activation=jax.nn.gelu,
    w_in_scale: jax.Array | None = None,
    w_out_scale: jax.Array | None = None,
) -> jax.Array:
    """Dropless routed expert MLP over ``[N, D]`` token rows.

    ``tokens``: [N, D] (already cast to the compute dtype);
    ``expert_idx``: [N] int routed expert per token; weights carry the
    leading [E, ...] expert axis.  Returns [N, D] in ``tokens.dtype`` —
    the caller applies router-prob scaling.  Gradients flow to tokens
    and all four weight leaves through ``ragged_dot``'s VJP; the integer
    routing path is non-differentiable exactly as the one-hot path is.

    ``w_in_scale``/``w_out_scale`` ([E, F] / [E, D] f32): weight-only
    int8 expert serving — ``w_in``/``w_out`` are then int8 and the
    per-expert per-output-channel scales fold into the activations
    AFTER each ragged matmul (each row multiplies its own expert's
    scale row, gathered by ``eids``), the same
    quantize-stays-in-the-dot recipe as the int8 KV cache's einsum
    (``models/transformer.py::_cached_attention_quant``): the int8→
    compute-dtype convert fuses into ``ragged_dot``'s operand read, so
    HBM only ever reads the int8 expert bytes.
    """
    n_experts = w_in.shape[0]
    order, inv_order, group_sizes = sort_by_expert(expert_idx, n_experts)
    xs = _permute_rows(tokens, order, inv_order)
    eids = jnp.take(expert_idx, order, axis=0)
    dt = tokens.dtype
    h = lax.ragged_dot(xs, w_in.astype(dt), group_sizes)
    if w_in_scale is not None:
        h = h * jnp.take(w_in_scale, eids, axis=0).astype(dt)
    h = activation(h + jnp.take(b_in.astype(dt), eids, axis=0))
    ys = lax.ragged_dot(h, w_out.astype(dt), group_sizes)
    if w_out_scale is not None:
        ys = ys * jnp.take(w_out_scale, eids, axis=0).astype(dt)
    ys = ys + jnp.take(b_out.astype(dt), eids, axis=0)
    return _permute_rows(ys, inv_order, order)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _scatter_rows(x: jax.Array, idx: jax.Array, n_out: int):
    """Rows of ``x`` scattered to UNIQUE slots ``idx`` of a zero
    [n_out, D] buffer.  Because the slots are unique (an injection —
    the EP slotting map below guarantees it), the exact cotangent is
    the gather back by ``idx`` — never the generic scatter-add
    transpose (row-at-a-time on TPU, ~22 GB/s measured; see
    ``_permute_rows``)."""
    return jnp.zeros((n_out, x.shape[1]), x.dtype).at[idx].set(x)


def _scatter_rows_fwd(x, idx, n_out):
    return _scatter_rows(x, idx, n_out), idx


def _scatter_rows_bwd(n_out, idx, ct):
    return jnp.take(ct, idx, axis=0), None


_scatter_rows.defvjp(_scatter_rows_fwd, _scatter_rows_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _gather_rows(x: jax.Array, idx: jax.Array, n_in: int):
    """``x[idx]`` where ``idx`` addresses UNIQUE rows of an [n_in, D]
    buffer: the exact cotangent is the scatter-set back (unaddressed
    rows correctly get zero), avoiding ``jnp.take``'s scatter-add
    transpose."""
    return jnp.take(x, idx, axis=0)


def _gather_rows_fwd(x, idx, n_in):
    return jnp.take(x, idx, axis=0), idx


def _gather_rows_bwd(n_in, idx, ct):
    return jnp.zeros((n_in, ct.shape[1]), ct.dtype).at[idx].set(ct), None


_gather_rows.defvjp(_gather_rows_fwd, _gather_rows_bwd)


def grouped_expert_mlp_ep(
    tokens: jax.Array,
    expert_idx: jax.Array,
    w_in: jax.Array,
    b_in: jax.Array,
    w_out: jax.Array,
    b_out: jax.Array,
    *,
    expert_axis: str,
    n_experts_global: int,
    activation=jax.nn.gelu,
    slots_per_owner: int | None = None,
    return_dropped: bool = False,
) -> jax.Array | tuple[jax.Array, jax.Array]:
    """Dropless routed expert MLP under REAL expert parallelism.

    Must run inside a ``shard_map`` with ``expert_axis`` bound (fully
    manual over it).  Each device holds ``tokens`` [N_local, D] — its
    shard of the global batch — and the weights of its
    ``E_local = n_experts_global / ep`` experts (leading axis of
    ``w_in``/``b_in``/``w_out``/``b_out`` is the LOCAL expert count;
    device r owns global experts [r·E_local, (r+1)·E_local)).
    ``expert_idx`` routes each local token to a GLOBAL expert.

    The dance (all static shapes, exact inverses on the way back):

    1. **Slot**: token i goes to owner ``o = expert // E_local`` at
       slot ``o·S + rank_within_owner(i)`` with ``S = N_local`` send
       slots per owner — a device can send at most all its rows to one
       owner, so the bound can never overflow: **provably dropless**,
       unlike the einsum path's per-expert capacity.  The slot map is
       injective, so scatter/gather custom VJPs are exact inverses.
    2. **all_to_all** along ``expert_axis``: chunk o of the send
       buffer lands on device o — the token all-to-all the einsum path
       leaves to the GSPMD partitioner, written explicitly.
    3. **Group**: received rows counting-sort by LOCAL expert with a
       trailing dummy group for empty slots; ``lax.ragged_dot`` covers
       only the real groups (uncovered trailing rows produce zeros
       with zero gradients — verified semantics).
    4. **Return**: un-sort, all_to_all back, gather by the slot map.

    Returns [N_local, D] in ``tokens.dtype`` (router-prob scaling is
    the caller's, as in :func:`grouped_expert_mlp`).  The ICI cost is
    2 all_to_alls of ep·S rows; the matmul padding is bounded by the
    receive buffer (ep·S rows vs ~N_local useful on a balanced
    router).  Reference: the all-to-all pattern is Switch/GShard
    dispatch (SURVEY.md §2.3 marks EP absent in the reference — this
    is beyond-parity capability).

    ``slots_per_owner`` (ADVICE r4): by default S = N_local send slots
    per owner — provably dropless, but the all-to-all moves ep·N_local
    rows (~ep× the useful rows on a balanced router).  Setting S lower
    (e.g. ``2·N_local/ep``) bounds the wire bytes and matmul padding at
    the cost of Switch-style drops: a token whose within-owner rank
    exceeds S gets ZERO output (residual pass-through) and zero
    gradients — the same overflow semantics as einsum capacity, applied
    per OWNER at the transport instead of per expert.
    ``return_dropped=True`` additionally returns the local dropped-row
    count (int32 scalar) for monitoring.
    """
    ep = lax.axis_size(expert_axis)
    e_local = w_in.shape[0]
    if e_local * ep != n_experts_global:
        raise ValueError(
            f"local expert axis {e_local} x mesh axis {ep} != "
            f"n_experts_global {n_experts_global}"
        )
    n, d = tokens.shape
    if slots_per_owner is not None and not 1 <= slots_per_owner <= n:
        raise ValueError(
            f"slots_per_owner must be in [1, N_local={n}], got "
            f"{slots_per_owner} (None = dropless N_local slots)"
        )
    S = n if slots_per_owner is None else slots_per_owner
    e0 = lax.axis_index(expert_axis) * e_local

    owner = expert_idx // e_local  # [N] destination device on the axis
    oh = jax.nn.one_hot(owner, ep, dtype=jnp.int32)
    rank = jnp.sum(jnp.cumsum(oh, axis=0) * oh, axis=1) - 1  # within-owner
    # ONE dispatch form for both modes (tested bitwise-equal at ample
    # slots): overflowing rows — impossible when S = N_local, since
    # rank < n always — route to a TRASH slot past the buffer; the
    # [:ep*S] slice discards it, so (a) receivers never see them and
    # (b) the slice's transpose zeroes their cotangent.  _scatter_rows'
    # unique-slot contract is violated only at the trash slot, whose
    # value and cotangent are both dead.  Expert ids ride beside the
    # rows; -1 marks never-written slots.
    valid = rank < S
    slot = jnp.where(valid, owner * S + rank, ep * S)
    send = _scatter_rows(tokens, slot, ep * S + 1)[:ep * S]
    send_ids = jnp.full((ep * S + 1,), -1, jnp.int32).at[slot].set(
        expert_idx
    )[:ep * S]
    n_dropped = jnp.sum((~valid).astype(jnp.int32))
    recv = lax.all_to_all(
        send.reshape(ep, S, d), expert_axis, 0, 0, tiled=False
    ).reshape(ep * S, d)
    recv_ids = lax.all_to_all(
        send_ids.reshape(ep, S, 1), expert_axis, 0, 0, tiled=False
    ).reshape(ep * S)

    # Local grouping: dummy group (= e_local) LAST, so ragged_dot's
    # group_sizes[:e_local] cover exactly the real rows.
    le = jnp.where(recv_ids >= 0, recv_ids - e0, e_local)
    order, inv_order, group_sizes = sort_by_expert(le, e_local + 1)
    xs = _permute_rows(recv, order, inv_order)
    eids = jnp.take(le, order, axis=0)  # sorted; dummies trail
    gs = group_sizes[:e_local]
    dt = tokens.dtype
    # Biases extended with a zero row so dummy rows stay inert.
    b_in_x = jnp.concatenate([b_in, jnp.zeros_like(b_in[:1])]).astype(dt)
    b_out_x = jnp.concatenate([b_out, jnp.zeros_like(b_out[:1])]).astype(dt)
    h = lax.ragged_dot(xs, w_in.astype(dt), gs)
    h = activation(h + jnp.take(b_in_x, eids, axis=0))
    ys = lax.ragged_dot(h, w_out.astype(dt), gs)
    ys = ys + jnp.take(b_out_x, eids, axis=0)
    # Dummy rows stay exactly zero: ragged_dot leaves uncovered trailing
    # rows zero and their bias row (index e_local of the extended bias)
    # is zero.  They are also never gathered on the sender side — the
    # slot map only reads slots it wrote — so BOTH properties protect
    # the result independently.
    ys = _permute_rows(ys, inv_order, order)
    back = lax.all_to_all(
        ys.reshape(ep, S, d), expert_axis, 0, 0, tiled=False
    ).reshape(ep * S, d)
    # Dropped rows gather the appended zero row (their slot is the
    # trash index ep*S): zero output, and the concat transpose discards
    # the trash cotangent — zero gradients, matching the forward's
    # pass-through semantics.  (Unbounded: no row points at the trash
    # index, so the appended zero row is inert — the single code path
    # the ample-slots test pins bitwise against the r4 form.)
    back = jnp.concatenate([back, jnp.zeros((1, d), back.dtype)])
    y = _gather_rows(back, slot, ep * S + 1)
    return (y, n_dropped) if return_dropped else y
