"""Rank-0-gated logging.

The reference prints from every rank (its banner at ``part2/2a/main.py:200-203``
even prints world size/rank per worker).  Under multi-host JAX every process
runs the same program, so the idiomatic surface is: informational prints from
process 0 only, with an escape hatch for per-rank diagnostics.
"""

from __future__ import annotations

import logging
import sys


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def rank0_print(*args, all_ranks: bool = False, **kwargs) -> None:
    """print() on process 0 only (or all ranks when all_ranks=True)."""
    if all_ranks or _process_index() == 0:
        print(*args, **kwargs)
        sys.stdout.flush()


def get_logger(name: str = "dml_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(name)s %(levelname)s %(message)s")
        )
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
    return logger
