"""Jitted LM train step over a 2-D (data × sequence) mesh.

The CNN step (``train/step.py``) distributes over one data axis — the
reference's whole capability surface.  Language models add the second
axis: context parallelism.  Here the batch shards over ``data_axis`` AND
the sequence over ``seq_axis``; attention runs as the exact ppermute ring
(``ops/ring_attention.py``) along the sequence axis, and gradients
all-reduce (pmean) over *both* axes — with mean per-token loss, the
gradient of the global mean is exactly the two-axis pmean of local grads.

State stays replicated (pure data/context parallelism; tensor-parallel
sharded params are ``parallel/tensor_parallel.py``'s job).  The SGD
update is the same hand-rolled kernel the CNN path uses.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_machine_learning_tpu.train.common import (
    guard_update,
    tree_all_finite,
)
from distributed_machine_learning_tpu.train.losses import lm_cross_entropy
from distributed_machine_learning_tpu.train.optimizers import update_fn_for_config
from distributed_machine_learning_tpu.train.state import TrainState
from distributed_machine_learning_tpu.runtime.mesh import (
    shard_map_no_check as _shard_map,
)

DATA_AXIS = "batch"
SEQ_AXIS = "seq"

# Dynamic loss-scale clamps: the scale never collapses below 1 (an
# unscaled loss must always be representable) and never exceeds 2^24
# (past that, fp32 gradient accumulation itself loses integer precision).
_MIN_SCALE = 1.0
_MAX_SCALE = 2.0**24


@struct.dataclass
class DynamicScaleState:
    """A TrainState plus dynamic loss-scale bookkeeping.

    The bf16 LM path underflows small gradients; the standard fix is to
    multiply the loss by ``loss_scale`` before the backward pass, divide
    the gradients by it after, and adapt: halve on overflow (non-finite
    gradients — the update is skipped, riding the same guard path),
    double after ``growth_interval`` consecutive good steps.  Kept as a
    wrapper rather than new TrainState fields so every existing
    checkpoint, scheme, and test keeps its pytree structure; the step
    delegates (``step``/``params``/``config``) so drivers that only read
    those fields (``train/loop.py``) work on either.
    """

    inner: TrainState
    loss_scale: jax.Array   # f32 scalar
    good_steps: jax.Array   # i32 scalar: consecutive finite-grad steps
    growth_interval: int = struct.field(pytree_node=False, default=200)

    @property
    def step(self):
        return self.inner.step

    @property
    def params(self):
        return self.inner.params

    @property
    def config(self):
        return self.inner.config


def with_dynamic_scale(state: TrainState, init_scale: float = 2.0**15,
                       growth_interval: int = 200) -> DynamicScaleState:
    """Wrap a TrainState for ``make_lm_train_step(dynamic_scale=True)``."""
    if init_scale < _MIN_SCALE or init_scale > _MAX_SCALE:
        raise ValueError(
            f"init_scale must be in [{_MIN_SCALE}, {_MAX_SCALE}], got "
            f"{init_scale}"
        )
    if growth_interval < 1:
        raise ValueError(
            f"growth_interval must be >= 1, got {growth_interval}"
        )
    return DynamicScaleState(
        inner=state,
        loss_scale=jnp.asarray(init_scale, jnp.float32),
        good_steps=jnp.zeros((), jnp.int32),
        growth_interval=growth_interval,
    )


def unwrap_dynamic_scale(state):
    """The plain TrainState inside (identity for an unwrapped state) —
    for checkpointing/eval, which know nothing of the scaler."""
    return state.inner if isinstance(state, DynamicScaleState) else state


def lm_loss(model, params, tokens, targets,
            fused_ce_chunks: int | None = None):
    """The LM training loss — one definition shared by the replicated
    step below and the ZeRO-3 LM step (``parallel/fsdp.py``).

    With ``fused_ce_chunks`` the head+loss are fused: the [B, L, vocab]
    logits are never materialized — the model returns post-ln_f hidden
    states and ``ops/fused_ce.py`` scans the vocab in chunks.
    """
    if fused_ce_chunks:
        from distributed_machine_learning_tpu.ops.fused_ce import (
            fused_linear_cross_entropy,
        )

        hidden = model.apply(
            {"params": params}, tokens, train=True, return_hidden=True
        )
        E = hidden.shape[-1]
        return fused_linear_cross_entropy(
            hidden.reshape(-1, E),
            params["lm_head"]["kernel"],
            params["lm_head"]["bias"],
            targets.reshape(-1),
            fused_ce_chunks,
        )
    logits = model.apply({"params": params}, tokens, train=True)
    return lm_cross_entropy(logits, targets)


def _lm_step_impl(model, state: TrainState, tokens, targets, *, axis_names,
                  fused_ce_chunks: int | None = None, guard: bool = False):
    def loss_fn(params):
        return lm_loss(model, params, tokens, targets, fused_ce_chunks)

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    if axis_names:
        grads = lax.pmean(grads, axis_names)
        loss = lax.pmean(loss, axis_names)
    new_params, new_momentum = update_fn_for_config(state.config)(
        state.params, state.momentum, grads, state.config, step=state.step
    )
    new_state = state.replace(
        params=new_params, momentum=new_momentum, step=state.step + 1
    )
    if guard:
        # Non-finite gradients skip the update wholesale (step counter
        # included); the non-finite loss still returns so the host can
        # count the skip.  Post-pmean grads ⇒ replicated decision.
        new_state = guard_update(tree_all_finite(grads), new_state, state)
    return new_state, loss


def _lm_scaled_step_impl(model, sstate: DynamicScaleState, tokens, targets,
                         *, axis_names, fused_ce_chunks: int | None = None):
    """The dynamic-loss-scaled LM step (guard always on).

    Loss is scaled BEFORE the backward pass (so bf16 gradients sit in
    representable range), gradients unscaled after the cross-axis pmean;
    overflow (any non-finite gradient) skips the update and halves the
    scale, ``growth_interval`` consecutive good steps double it.
    """
    state = sstate.inner
    scale = sstate.loss_scale

    def loss_fn(params):
        return (
            lm_loss(model, params, tokens, targets, fused_ce_chunks)
            * scale
        )

    scaled_loss, grads = jax.value_and_grad(loss_fn)(state.params)
    if axis_names:
        grads = lax.pmean(grads, axis_names)
        scaled_loss = lax.pmean(scaled_loss, axis_names)
    grads = jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) / scale).astype(g.dtype), grads
    )
    finite = tree_all_finite(grads)
    new_params, new_momentum = update_fn_for_config(state.config)(
        state.params, state.momentum, grads, state.config, step=state.step
    )
    new_inner = guard_update(
        finite,
        state.replace(params=new_params, momentum=new_momentum,
                      step=state.step + 1),
        state,
    )
    grown = sstate.good_steps + 1 >= sstate.growth_interval
    new_scale = jnp.where(
        finite,
        jnp.where(grown, jnp.minimum(scale * 2.0, _MAX_SCALE), scale),
        jnp.maximum(scale * 0.5, _MIN_SCALE),
    )
    new_good = jnp.where(
        finite, jnp.where(grown, 0, sstate.good_steps + 1), 0
    )
    new_sstate = DynamicScaleState(
        inner=new_inner, loss_scale=new_scale, good_steps=new_good,
        growth_interval=sstate.growth_interval,
    )
    # Report the UNSCALED loss (non-finite on overflow steps, which is
    # how the host observes the backoff).
    return new_sstate, scaled_loss / scale


def make_lm_train_step(
    model,
    mesh: Mesh | None = None,
    data_axis: str = DATA_AXIS,
    seq_axis: str = SEQ_AXIS,
    fused_ce_chunks: int | None = None,
    guard_nonfinite: bool = False,
    dynamic_scale: bool = False,
):
    """Build ``step(state, tokens, targets) -> (state, loss)``.

    Without a mesh: plain jit (model must use ``attn_impl="dense"``).
    With a mesh: shard_map over (data_axis, seq_axis); tokens/targets
    sharded [data, seq], state replicated.  A ring-attention model shards
    the sequence for real; a dense model on a seq-axis-size-1 mesh is the
    pure-DP special case.

    ``fused_ce_chunks``: if set (>= 1), compute the loss fused with the
    lm_head over this many vocab chunks (``ops/fused_ce.py``) — the
    [B, L, vocab] logits are never materialized.

    ``guard_nonfinite``: compile the non-finite-gradient guard into the
    step — non-finite (post-pmean) gradients skip the update (state and
    step counter unchanged) instead of poisoning the params.

    ``dynamic_scale``: the bf16 path's dynamic loss scaling (implies the
    guard).  The step then operates on a :class:`DynamicScaleState` —
    wrap the initial state with :func:`with_dynamic_scale` and unwrap
    with :func:`unwrap_dynamic_scale` for checkpointing/eval.  Overflow
    halves the scale and skips the update; ``growth_interval``
    consecutive good steps double it (clamped to [1, 2^24]).
    """
    if fused_ce_chunks is not None and fused_ce_chunks < 1:
        raise ValueError(
            f"fused_ce_chunks must be >= 1 (got {fused_ce_chunks}); "
            "use None for the unfused loss"
        )
    if dynamic_scale:
        base_impl = partial(_lm_scaled_step_impl, model,
                            fused_ce_chunks=fused_ce_chunks)
    else:
        base_impl = partial(_lm_step_impl, model,
                            fused_ce_chunks=fused_ce_chunks,
                            guard=guard_nonfinite)
    if mesh is None:
        impl = partial(base_impl, axis_names=())
        return jax.jit(impl, donate_argnums=(0,))

    missing = [a for a in (data_axis, seq_axis) if a not in mesh.axis_names]
    if missing:
        raise ValueError(
            f"LM mesh must have axes ({data_axis!r}, {seq_axis!r}); missing "
            f"{missing} in {mesh.axis_names} (use axis_shape=(1, n) or (n, 1) "
            "to disable one dimension)"
        )
    axis_names = (data_axis, seq_axis)
    if model.attn_impl == "ulysses" and model.n_heads % mesh.shape[seq_axis]:
        # Fail at build time, not first-step trace time (ops/ulysses.py
        # would raise the same constraint inside shard_map tracing).
        raise ValueError(
            f"Ulysses needs n_heads divisible by the sequence-axis size: "
            f"{model.n_heads} heads over {mesh.shape[seq_axis]} devices"
        )
    if (
        model.attn_impl not in ("ring", "ring_flash", "ulysses")
        and mesh.shape[seq_axis] > 1
    ):
        # Dense attention only sees its local chunk with offset-0 positions:
        # sharding the sequence under it would be silently wrong, not slow.
        raise ValueError(
            f"dense-attention model cannot shard the sequence: mesh axis "
            f"{seq_axis!r} has size {mesh.shape[seq_axis]} > 1; use "
            'attn_impl="ring"/"ring_flash"/"ulysses" or an axis_shape '
            "with seq size 1"
        )
    impl = partial(base_impl, axis_names=axis_names)
    batch_spec = P(data_axis, seq_axis)
    sharded = _shard_map(
        impl,
        mesh=mesh,
        in_specs=(P(), batch_spec, batch_spec),
        out_specs=(P(), P()),
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_lm_eval_step(model):
    """Jitted LM eval: ``(params, tokens, targets) -> (nll_sum, count)``.

    Returns the *sum* of per-token negative log-likelihoods and the
    token count, so the caller can pool across batches of any size and
    compute exact corpus-level perplexity ``exp(total_nll / total_count)``
    (``train/loop.py::evaluate_lm``) — the LM analogue of the CNN's
    ``test_model`` protocol (``part1/main.py:62-77``).  Params are
    replicated in the dp/ring/ulysses schemes, so eval runs dense on one
    program (the model is cloned to dense attention).
    """
    dense = model.clone(attn_impl="dense") if model.attn_impl != "dense" else model

    @jax.jit
    def eval_step(params, tokens, targets):
        logits = dense.apply({"params": params}, tokens, train=False)
        # mean CE × count = exact NLL sum; one shared loss implementation
        # keeps eval ppl and training loss from ever diverging.
        nll = lm_cross_entropy(logits, targets) * targets.size
        return nll, jnp.asarray(targets.size, jnp.int32)

    return eval_step


def shard_lm_batch(
    mesh: Mesh,
    tokens,
    targets,
    data_axis: str = DATA_AXIS,
    seq_axis: str = SEQ_AXIS,
):
    """Place [B, L] token/target arrays: batch over data axis, sequence
    over the ring axis."""
    sharding = NamedSharding(mesh, P(data_axis, seq_axis))
    return (
        jax.device_put(jnp.asarray(tokens), sharding),
        jax.device_put(jnp.asarray(targets), sharding),
    )


def init_lm_state(model, seed: int = 69143, batch: int = 1, seq_len: int = 8,
                  config=None):
    """Initialize LM params/state from the shared seed.

    Initialization always runs the dense path (no mesh needed): parameter
    shapes are independent of the attention implementation.  ``config``:
    optional optimizer config (default SGD parity; pass ``AdamWConfig()``
    for the LM-standard AdamW — the step dispatches on the config type).
    """
    dense = model.clone(attn_impl="dense") if model.attn_impl != "dense" else model
    rng = jax.random.PRNGKey(seed)
    init_rng, state_rng = jax.random.split(rng)
    tokens = jnp.zeros((batch, seq_len), jnp.int32)
    variables = dense.init(init_rng, tokens, train=False)
    return TrainState.create(
        params=variables["params"], rng=state_rng, config=config
    )
