"""Digital-twin network model: virtual clock, gray link state, and the
calibration proof (ISSUE 20).

Three layers, cheapest first:

- :class:`VirtualClock` / :class:`NetModel` unit contracts — monotone
  virtual time, per-axis link pricing, gray mutations (degrade / flaky
  / bw-collapse / restore) and their exact arithmetic;
- the **calibration regression**: the cost model's hop schedule
  (``Topology.plan_hops``) must re-derive, byte for byte, the static
  per-axis wire accounting (``ring_wire_bytes_by_axis`` /
  ``topology_wire_bytes``) that DML103 pins against compiled HLO — for
  every world-8 cell of the round-11 bench grid (2x4/4x2 ×
  none/bf16/int8/topk), against the NUMBERS RECORDED in
  ``BENCH_r11_hier.json``, not regenerated ones;
- the **measured-ordering check**: wherever the model predicts the
  hierarchical plan beats the flat ring (every lossy cell at the bench
  bucket), the recorded p50s agree.  Exact cells ran halving-doubling
  in the bench, so flat-vs-hier has no measured row there — the model
  is only held to orderings the bench actually measured.

The twin never sleeps and never reads a real clock — dmlcheck DML016
enforces that statically for ``runtime/netmodel.py``; these tests pin
the behavioral side (same inputs, same trajectory, no wall-time
dependence).
"""

from __future__ import annotations

import json
import os

import pytest

from distributed_machine_learning_tpu.ops.ring import (
    ring_wire_bytes_by_axis,
)
from distributed_machine_learning_tpu.ops.topology import (
    DEFAULT_LINK_MODEL,
    LinkModel,
    Topology,
    topology_wire_bytes,
)
from distributed_machine_learning_tpu.runtime.netmodel import (
    NetModel,
    VirtualClock,
)

HERE = os.path.dirname(os.path.abspath(__file__))
BENCH_R11 = os.path.join(os.path.dirname(HERE), "BENCH_r11_hier.json")


# ---------------------------------------------------------------------------
# VirtualClock
# ---------------------------------------------------------------------------


def test_virtual_clock_is_monotone_and_never_rewinds():
    clock = VirtualClock()
    assert clock.now() == 0.0
    assert clock.advance(1.5) == 1.5
    assert clock.advance(0.0) == 1.5
    with pytest.raises(ValueError):
        clock.advance(-0.1)
    assert clock.advance_to(1.0) == 1.5  # monotone max, no rewind
    assert clock.advance_to(3.0) == 3.0
    assert clock.now() == 3.0
    assert VirtualClock(start=7.0).now() == 7.0


# ---------------------------------------------------------------------------
# NetModel: link pricing and gray state
# ---------------------------------------------------------------------------


def test_link_axis_follows_inner_major_node_grouping():
    nm = NetModel(8, inner=4)
    assert nm.node_of(3) == 0 and nm.node_of(4) == 1
    assert nm.link_axis(0, 3) == "inner"
    assert nm.link_axis(3, 4) == "outer"
    assert nm.link_axis(7, 0) == "outer"
    with pytest.raises(ValueError):
        NetModel(6, inner=4)  # world must be a multiple of inner


def test_link_time_arithmetic_is_exact():
    lm = LinkModel()
    nm = NetModel(8, inner=4, link=lm)
    nbytes = 1 << 20
    assert nm.link_time(0, 1, nbytes) == pytest.approx(
        lm.inner_overhead_s + nbytes / lm.inner_bytes_per_s)
    assert nm.link_time(3, 4, nbytes) == pytest.approx(
        lm.outer_overhead_s + nbytes / lm.outer_bytes_per_s)
    # degrade: latency x k, bandwidth untouched.
    nm.degrade_link(0, 1, 50.0)
    assert nm.link_time(0, 1, nbytes) == pytest.approx(
        50.0 * lm.inner_overhead_s + nbytes / lm.inner_bytes_per_s)
    # the reverse direction is a different link.
    assert nm.link_time(1, 0, nbytes) == pytest.approx(
        lm.inner_overhead_s + nbytes / lm.inner_bytes_per_s)
    # flaky: deterministic expected retransmissions 1/(1-p).
    nm.flaky_link(1, 0, 0.5)
    assert nm.link_time(1, 0, nbytes) == pytest.approx(
        2.0 * (lm.inner_overhead_s + nbytes / lm.inner_bytes_per_s))
    # bw_collapse: every link touching the node divides its bandwidth.
    nm.bw_collapse(1, 4.0)
    assert nm.link_time(3, 4, nbytes) == pytest.approx(
        lm.outer_overhead_s + nbytes / (lm.outer_bytes_per_s / 4.0))
    assert nm.link_time(4, 5, nbytes) == pytest.approx(
        lm.inner_overhead_s + nbytes / (lm.inner_bytes_per_s / 4.0))
    # restore clears the directed link's latency and flakiness.
    nm.restore_link(0, 1)
    assert nm.link_time(0, 1, nbytes) == pytest.approx(
        lm.inner_overhead_s + nbytes / lm.inner_bytes_per_s)


def test_gray_state_validation_rejects_nonsense():
    nm = NetModel(4)
    with pytest.raises(ValueError):
        nm.degrade_link(0, 1, 0.5)
    with pytest.raises(ValueError):
        nm.flaky_link(0, 1, 1.0)
    with pytest.raises(ValueError):
        nm.bw_collapse(0, 0.0)


def test_degraded_links_reports_every_non_baseline_link():
    nm = NetModel(8, inner=4)
    assert nm.degraded_links() == []
    nm.degrade_link(3, 4, 10.0)
    nm.flaky_link(0, 1, 0.25)
    nm.bw_collapse(1, 8.0)
    rows = {(r["src"], r["dst"]): r for r in nm.degraded_links()}
    assert (3, 4) in rows and rows[(3, 4)]["latency_mult"] == 10.0
    assert rows[(3, 4)]["axis"] == "outer"
    assert rows[(0, 1)]["flaky_p"] == 0.25
    # the collapsed node surfaces through its representative ring link.
    assert rows[(4, 5)]["bw_div"] == 8.0
    nm.restore_link(3, 4)
    nm.restore_link(0, 1)
    assert [r["bw_div"] for r in nm.degraded_links()] == [8.0]


def test_step_time_inflates_only_ranks_on_the_gray_link():
    """The straggler signal: per-device ring accounting means a gray
    outgoing link inflates exactly its source rank's modeled step."""
    nm = NetModel(16, inner=4, compute_s=0.002, step_bytes=4 << 20)
    base = [nm.step_time(r) for r in range(16)]
    nm.degrade_link(5, 6, 1000.0)
    after = [nm.step_time(r) for r in range(16)]
    assert after[5] > 10.0 * base[5]
    for r in range(16):
        if r != 5:
            assert after[r] == pytest.approx(base[r])
    nm.restore_link(5, 6)
    assert [nm.step_time(r) for r in range(16)] == pytest.approx(base)


def test_step_time_is_pure_virtual_arithmetic():
    """Same model, same gray state => bit-identical step times: the
    twin's determinism rests on there being NO hidden clock or RNG in
    the cost path."""
    def trajectory():
        nm = NetModel(8, inner=2, compute_s=0.001)
        out = [[nm.step_time(r) for r in range(8)]]
        nm.degrade_link(2, 3, 50.0)
        nm.flaky_link(6, 7, 0.5)
        out.append([nm.step_time(r) for r in range(8)])
        nm.restore_link(2, 3)
        out.append([nm.step_time(r) for r in range(8)])
        return out

    assert trajectory() == trajectory()


# ---------------------------------------------------------------------------
# Calibration: the cost model vs the audited wire accounting and the
# measured round-11 grid
# ---------------------------------------------------------------------------

N_ELEMS = 8521          # the vggtest gradient the round-11 grid timed
BUCKET_MB = 25          # one bucket covers the whole gradient
WORLD = 8


def _bench_rows():
    with open(BENCH_R11) as f:
        rows = json.load(f)
    return {
        (r["topology"], r["compress"]): r
        for r in rows
        if isinstance(r, dict) and r.get("world") == WORLD
        and "topology" in r
    }


def _topo(spec: str, compress: str) -> Topology:
    inner, outer = (int(x) for x in spec.split("x"))
    return Topology(inner=inner, outer=outer, outer_scheme=compress)


@pytest.mark.parametrize("spec", ["2x4", "4x2"])
@pytest.mark.parametrize("compress", ["none", "bf16", "int8", "topk"])
def test_plan_hops_rederives_the_recorded_per_axis_bytes(spec, compress):
    """The twin's hop schedule must account the SAME bytes per axis as
    the static accounting DML103 pins to compiled HLO — asserted
    against the numbers recorded in BENCH_r11_hier.json, so a cost-model
    refactor that silently re-prices an axis fails here even if it
    stays self-consistent."""
    row = _bench_rows()[(spec, compress)]
    topo = _topo(spec, compress)
    bucket_bytes = BUCKET_MB << 20
    plan = topo.select(N_ELEMS * 4)
    assert plan == row["plan"], (
        f"{spec}/{compress}: selector chose {plan}, bench recorded "
        f"{row['plan']}")
    by_axis: dict[str, int] = {}
    for axis, _dist, payload in topo.plan_hops(N_ELEMS * 4, plan):
        by_axis[axis] = by_axis.get(axis, 0) + payload
    assert by_axis == row["wire_bytes_by_axis"]
    assert by_axis == topology_wire_bytes(N_ELEMS, topo, bucket_bytes)
    assert by_axis == ring_wire_bytes_by_axis(
        N_ELEMS, WORLD, bucket_bytes=bucket_bytes, topology=topo)


@pytest.mark.parametrize("compress", ["bf16", "int8", "topk"])
def test_model_predicted_ordering_matches_measured_p50(compress):
    """Wherever the model predicts hier beats flat, the measured
    round-11 p50s must agree.  Restricted to lossy cells: those are
    the only cells whose bench rows ran the hierarchical plan (exact
    cells selected hd), so they are the only flat-vs-hier orderings
    the grid measured."""
    rows = _bench_rows()
    link = DEFAULT_LINK_MODEL
    for spec in ("2x4", "4x2"):
        topo = _topo(spec, compress)
        t_hier = topo.predict_bucket_time(N_ELEMS * 4, plan="hier",
                                          link=link)
        t_flat = topo.predict_bucket_time(N_ELEMS * 4, plan="flat",
                                          link=link)
        assert t_hier < t_flat, (
            f"{spec}/{compress}: model stopped predicting hier<flat")
        measured_hier = rows[(spec, compress)]["iter_p50_s"]
        measured_flat = rows[("flat", compress)]["iter_p50_s"]
        assert measured_hier < measured_flat, (
            f"{spec}/{compress}: model predicts hier<flat but the "
            f"recorded p50s disagree ({measured_hier:.5f} vs "
            f"{measured_flat:.5f}) — recalibrate LinkModel")


def test_netmodel_prices_links_with_the_selector_link_model():
    """One cost model, two consumers: the twin's per-link pricing must
    be the SAME LinkModel arithmetic ``Topology.select`` optimizes
    over, or the simulated pod and the selector drift apart."""
    nm = NetModel(8, inner=4)
    lm = DEFAULT_LINK_MODEL
    assert nm.link.permute_time("inner", 1, 4096) == pytest.approx(
        lm.permute_time("inner", 1, 4096))
    assert nm.link_time(0, 1, 4096) == pytest.approx(
        lm.permute_time("inner", 1, 4096))
    assert nm.link_time(3, 4, 4096) == pytest.approx(
        lm.permute_time("outer", 1, 4096))
