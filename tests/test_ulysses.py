"""Ulysses sequence parallelism correctness: the all-to-all head-sharded
attention (ops/ulysses.py) must reproduce single-device dense causal
attention exactly, and a Ulysses TransformerLM on a sequence-sharded mesh
must match the unsharded dense model — same invariants as the ppermute
ring (tests/test_ring_attention.py), different collective."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
try:  # jax>=0.6 exposes shard_map at top level
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from distributed_machine_learning_tpu.ops.ring_attention import (
    dense_self_attention,
)
from distributed_machine_learning_tpu.ops.ulysses import (
    ulysses_self_attention,
)
from distributed_machine_learning_tpu.runtime.mesh import make_mesh

B, L, H, D = 2, 32, 8, 8


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(69143)
    shape = (B, L, H, D)
    return tuple(
        jnp.asarray(rng.standard_normal(shape, dtype=np.float32)) for _ in range(3)
    )


@pytest.mark.parametrize(
    "n_shards",
    [2,
     pytest.param(4, marks=pytest.mark.slow),
     pytest.param(8, marks=pytest.mark.slow)],
)
def test_ulysses_matches_dense(qkv, n_shards):
    q, k, v = qkv
    mesh = make_mesh(n_shards, axis_names=("seq",))
    uly = shard_map(
        lambda a, b, c: ulysses_self_attention(a, b, c, "seq", n_shards),
        mesh=mesh,
        in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"),
    )
    np.testing.assert_allclose(
        np.asarray(jax.jit(uly)(q, k, v)),
        np.asarray(dense_self_attention(q, k, v)),
        rtol=1e-5,
        atol=1e-6,
    )


def test_ulysses_flash_local_attention_matches_dense(qkv):
    """local_attn='flash': the per-device full-sequence attention runs
    the Pallas kernel — forward and all three gradients must still match
    single-device dense."""
    from distributed_machine_learning_tpu.runtime.mesh import (
        shard_map_no_check,
    )

    q, k, v = qkv
    n_shards = 2
    mesh = make_mesh(n_shards, axis_names=("seq",))
    # shard_map_no_check: pallas_call outputs carry no varying-mesh-axis
    # annotation, so the replication checker must be off (same reason the
    # LM train step uses it).
    uly = jax.jit(shard_map_no_check(
        lambda a, b, c: ulysses_self_attention(
            a, b, c, "seq", n_shards, local_attn="flash"
        ),
        mesh=mesh,
        in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"),
    ))
    np.testing.assert_allclose(
        np.asarray(uly(q, k, v)),
        np.asarray(dense_self_attention(q, k, v)),
        rtol=2e-5,
        atol=2e-6,
    )
    cot = jnp.asarray(
        np.random.default_rng(2).standard_normal((B, L, H, D),
                                                 dtype=np.float32)
    )
    g_u = jax.grad(lambda *a: jnp.sum(uly(*a) * cot), argnums=(0, 1, 2))(
        q, k, v
    )
    g_d = jax.grad(
        lambda *a: jnp.sum(dense_self_attention(*a) * cot), argnums=(0, 1, 2)
    )(q, k, v)
    for got, want, name in zip(g_u, g_d, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5,
            err_msg=f"d{name} mismatch",
        )


def test_ulysses_rejects_indivisible_heads(qkv):
    """H=8 over 8 devices is the limit; a 3-head tensor must be refused."""
    q, k, v = (a[:, :, :3] for a in qkv)
    mesh = make_mesh(2, axis_names=("seq",))
    uly = shard_map(
        lambda a, b, c: ulysses_self_attention(a, b, c, "seq", 2),
        mesh=mesh,
        in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"),
    )
    with pytest.raises(ValueError, match="divisible"):
        jax.jit(uly)(q, k, v)


def test_ulysses_single_shard_is_dense(qkv):
    q, k, v = qkv
    mesh = make_mesh(1, axis_names=("seq",))
    uly = shard_map(
        lambda a, b, c: ulysses_self_attention(a, b, c, "seq", 1),
        mesh=mesh,
        in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"),
    )
    np.testing.assert_allclose(
        np.asarray(jax.jit(uly)(q, k, v)),
        np.asarray(dense_self_attention(q, k, v)),
        rtol=1e-6,
        atol=1e-7,
    )


def test_ulysses_step_builder_validates_heads():
    """make_lm_train_step fails at build time, not first-step trace time."""
    from distributed_machine_learning_tpu.models.transformer import TransformerLM
    from distributed_machine_learning_tpu.train.lm_step import make_lm_train_step

    model = TransformerLM(
        vocab_size=64, d_model=36, n_layers=1, n_heads=6, attn_impl="ulysses"
    )
    mesh = make_mesh(8, axis_names=("batch", "seq"), axis_shape=(2, 4))
    with pytest.raises(ValueError, match="divisible"):
        make_lm_train_step(model, mesh=mesh)


def test_ulysses_lm_step_matches_dense():
    """Full train step: Ulysses LM on a (batch=2, seq=4) mesh takes the
    same first step as the unsharded dense LM (loss + params agree)."""
    from distributed_machine_learning_tpu.models.transformer import TransformerLM
    from distributed_machine_learning_tpu.train.lm_step import (
        init_lm_state,
        make_lm_train_step,
        shard_lm_batch,
    )

    rng = np.random.default_rng(7)
    toks = rng.integers(0, 64, (4, 33))
    x = toks[:, :-1].astype(np.int32)
    y = toks[:, 1:].astype(np.int32)

    dense = TransformerLM(vocab_size=64, d_model=32, n_layers=2, n_heads=8)
    dstate = init_lm_state(dense)
    dstep = make_lm_train_step(dense)
    dstate, dloss = dstep(dstate, jnp.asarray(x), jnp.asarray(y))

    uly = dense.clone(attn_impl="ulysses")
    mesh = make_mesh(8, axis_names=("batch", "seq"), axis_shape=(2, 4))
    ustate = init_lm_state(uly)
    ustep = make_lm_train_step(uly, mesh=mesh)
    ux, uy = shard_lm_batch(mesh, x, y)
    ustate, uloss = ustep(ustate, ux, uy)

    np.testing.assert_allclose(float(uloss), float(dloss), rtol=1e-5)
    flat_d = jax.tree.leaves(dstate.params)
    flat_u = jax.tree.leaves(ustate.params)
    for a, b in zip(flat_d, flat_u):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5
        )


def test_ulysses_gqa_narrow_path_matches_dense():
    """GQA through Ulysses: the narrow-K/V packed all-to-all path
    (Hkv % n == 0) and the widen-first fallback (Hkv % n != 0) both
    equal unsharded dense attention, on the dense AND flash local
    kernels (flash consumes the narrow K/V natively)."""
    rng = np.random.default_rng(9)
    for Hkv, n, Lg, local in (
        (4, 4, 32, "dense"),   # narrow path, dense local kernel
        (2, 4, 32, "dense"),   # widen-first fallback
        (4, 4, 512, "flash"),  # narrow path, flash local kernel
    ):
        rep = H // Hkv
        q = jnp.asarray(rng.standard_normal((B, Lg, H, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, Lg, Hkv, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, Lg, Hkv, D)), jnp.float32)
        ref = dense_self_attention(
            q, jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2)
        )
        from distributed_machine_learning_tpu.runtime.mesh import (
            shard_map_no_check,
        )

        mesh = make_mesh(n, axis_names=("seq",))
        fn = shard_map_no_check(
            lambda q, k, v, local=local: ulysses_self_attention(
                q, k, v, "seq", n, local_attn=local
            ),
            mesh=mesh,
            in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
            out_specs=P(None, "seq"),
        )
        out = fn(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
        )


def test_ulysses_rejects_non_divisor_kv_heads():
    q = jnp.zeros((1, 8, 8, 4))
    kv = jnp.zeros((1, 8, 3, 4))
    with pytest.raises(ValueError, match="multiple of K/V"):
        ulysses_self_attention(q, kv, kv, "seq", 1)
