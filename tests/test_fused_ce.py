"""Fused lm_head + cross-entropy (ops/fused_ce.py): the chunked scan
must reproduce the unfused loss AND its gradients to fp32 roundoff, for
every chunking (including non-dividing), and compose with the LM step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.ops.fused_ce import (
    fused_linear_cross_entropy,
)

T, E, V = 12, 8, 22


def _inputs(rng):
    h = jnp.asarray(rng.standard_normal((T, E)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((E, V)) * 0.3, jnp.float32)
    b = jnp.asarray(rng.standard_normal(V) * 0.1, jnp.float32)
    t = jnp.asarray(rng.integers(0, V, T), jnp.int32)
    return h, k, b, t


def _unfused(h, k, b, t):
    from distributed_machine_learning_tpu.train.losses import cross_entropy_loss

    return cross_entropy_loss(h @ k + b, t)


@pytest.mark.parametrize("num_chunks", [1, 2, 4, 7, 22])
def test_loss_matches_unfused(rng, num_chunks):
    # 7 and 22: chunk sizes that don't divide / exactly cover the vocab —
    # the -inf-bias padding path.
    h, k, b, t = _inputs(rng)
    fused = fused_linear_cross_entropy(h, k, b, t, num_chunks)
    np.testing.assert_allclose(
        float(fused), float(_unfused(h, k, b, t)), rtol=1e-6
    )


def test_grads_match_unfused(rng):
    h, k, b, t = _inputs(rng)
    gf = jax.grad(
        lambda h, k, b: fused_linear_cross_entropy(h, k, b, t, 4),
        argnums=(0, 1, 2),
    )(h, k, b)
    gu = jax.grad(
        lambda h, k, b: _unfused(h, k, b, t), argnums=(0, 1, 2)
    )(h, k, b)
    for a, b_ in zip(gf, gu):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=2e-5, atol=1e-7
        )


def test_bf16_hidden_fp32_loss(rng):
    h, k, b, t = _inputs(rng)
    loss = fused_linear_cross_entropy(h.astype(jnp.bfloat16), k, b, t, 2)
    assert loss.dtype == jnp.float32
    g = jax.grad(
        lambda hh: fused_linear_cross_entropy(hh, k, b, t, 2)
    )(h.astype(jnp.bfloat16))
    assert g.dtype == jnp.bfloat16


def test_lm_step_with_fused_ce_matches_dense(rng):
    from distributed_machine_learning_tpu.models.transformer import TransformerLM
    from distributed_machine_learning_tpu.train.lm_step import (
        init_lm_state,
        make_lm_train_step,
    )

    model = TransformerLM(vocab_size=32, d_model=16, n_layers=2, n_heads=2)
    toks = jnp.asarray(rng.integers(0, 32, (2, 9)), jnp.int32)
    s0 = init_lm_state(model)
    s1 = init_lm_state(model)
    dense_step = make_lm_train_step(model)
    fused_step = make_lm_train_step(model, fused_ce_chunks=3)
    s0, l0 = dense_step(s0, toks[:, :-1], toks[:, 1:])
    s1, l1 = fused_step(s1, toks[:, :-1], toks[:, 1:])
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(s0.params),
                    jax.tree_util.tree_leaves(s1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


def test_fused_ce_under_ring_context_parallel(rng):
    # Sequence-sharded: each shard's fused local mean pmeans to the
    # global mean, same as the unfused path.
    from distributed_machine_learning_tpu.models.transformer import TransformerLM
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh
    from distributed_machine_learning_tpu.train.lm_step import (
        init_lm_state,
        make_lm_train_step,
        shard_lm_batch,
    )

    mesh = make_mesh(4, ("batch", "seq"), (1, 4))
    model = TransformerLM(vocab_size=32, d_model=16, n_layers=2, n_heads=2,
                          attn_impl="ring")
    state = init_lm_state(model)
    toks = rng.integers(0, 32, (2, 17)).astype(np.int32)
    x, y = shard_lm_batch(mesh, toks[:, :-1], toks[:, 1:])
    step = make_lm_train_step(model, mesh=mesh, fused_ce_chunks=2)
    state, loss = step(state, x, y)

    dense = TransformerLM(vocab_size=32, d_model=16, n_layers=2, n_heads=2)
    ds = init_lm_state(dense)
    dstep = make_lm_train_step(dense)
    ds, dloss = dstep(ds, jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:]))
    np.testing.assert_allclose(float(loss), float(dloss), rtol=1e-5)


def test_fused_ce_chunk_validation(rng):
    from distributed_machine_learning_tpu.train.lm_step import make_lm_train_step
    from distributed_machine_learning_tpu.models.transformer import TransformerLM

    h, k, b, t = _inputs(rng)
    with pytest.raises(ValueError, match="num_chunks"):
        fused_linear_cross_entropy(h, k, b, t, 0)
    model = TransformerLM(vocab_size=32, d_model=16, n_layers=1, n_heads=2)
    with pytest.raises(ValueError, match="fused_ce_chunks"):
        make_lm_train_step(model, fused_ce_chunks=0)
    with pytest.raises(ValueError, match="fused_ce_chunks"):
        make_lm_train_step(model, fused_ce_chunks=-2)


def test_more_chunks_than_vocab(rng):
    # num_chunks > V: empty tail chunks are statically dropped.
    h, k, b, t = _inputs(rng)
    fused = fused_linear_cross_entropy(h, k, b, t, V + 9)
    np.testing.assert_allclose(
        float(fused), float(_unfused(h, k, b, t)), rtol=1e-6
    )


def test_bf16_kernel_stays_bf16_on_the_wire(rng):
    # The matmul input dtype is preserved (no fp32 kernel copy): grads
    # come back in the kernel's dtype and the loss is finite.
    h, k, b, t = _inputs(rng)
    kb = k.astype(jnp.bfloat16)
    loss, grads = jax.value_and_grad(
        lambda kk: fused_linear_cross_entropy(
            h.astype(jnp.bfloat16), kk, b, t, 3
        )
    )(kb)
    assert np.isfinite(float(loss))
    assert grads.dtype == jnp.bfloat16
