"""Flash-decode kernel: parity with the einsum cached attention at every
frontier position, int8-cache accuracy, and the generate-path dispatch
(ops/pallas/decode_attention.py — interpret mode on the CPU harness)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.models.transformer import (
    TransformerLM,
    _cached_attention,
)
from distributed_machine_learning_tpu.ops.pallas.decode_attention import (
    cached_flash_attention,
    decode_flash_qualifies,
    pick_block_s,
)


@pytest.mark.parametrize("pos", [0, 5, 63, 64, 200, 255])
def test_decode_kernel_matches_einsum(pos):
    """Slots past ``pos`` hold garbage on purpose: the kernel's frontier
    clamp + mask must make them invisible, exactly like the einsum's
    position mask."""
    rng = np.random.default_rng(0)
    B, S, H, Hkv, D = 2, 256, 8, 2, 64
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    ref = _cached_attention(q, k, v, jnp.asarray([pos], jnp.int32))
    out = cached_flash_attention(q, k, v, jnp.int32(pos))
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_decode_kernel_int8_cache_close_to_exact():
    rng = np.random.default_rng(1)
    B, S, H, Hkv, D = 1, 128, 4, 4, 32
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.float32)
    kf = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)
    vf = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.float32)

    def quant(t):
        amax = jnp.abs(t).max(axis=-1)
        s = jnp.where(amax > 0, amax / 127.0, 1.0)
        q8 = jnp.clip(jnp.round(t / s[..., None]), -127, 127).astype(jnp.int8)
        return q8, s

    k8, ks = quant(kf)
    v8, vs = quant(vf)
    ref = _cached_attention(q, kf, vf, jnp.asarray([100], jnp.int32))
    out = cached_flash_attention(q, k8, v8, jnp.int32(100), ks, vs)
    # int8 KV error budget: ~1% relative on the attention output.
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=0.05, atol=0.05
    )
    with pytest.raises(ValueError, match="k_scale"):
        cached_flash_attention(q, k8, v8, jnp.int32(100))


def test_block_picker_and_dispatch_rule():
    assert pick_block_s(2048) == 512
    assert pick_block_s(2208) is None  # no 128-multiple divisor
    assert pick_block_s(4) == 4
    assert pick_block_s(128) == 128
    assert decode_flash_qualifies(2048)
    assert decode_flash_qualifies(69)  # small cache: one full block
    assert not decode_flash_qualifies(2208)  # long + untileable: einsum


def _greedy(model, params, prompt, n, kv_dtype=None):
    from distributed_machine_learning_tpu.inference.generate import generate

    m = model.clone(kv_cache_dtype=kv_dtype)
    return np.asarray(generate(m, params, prompt, n))


def test_generate_int8_kv_cache_matches_full_precision():
    """End-to-end: int8 KV cache generation agrees with the f32-cache
    run on a trained-scale-free tiny model (greedy decoding is stable
    under the ~1% KV error at these sizes)."""
    model = TransformerLM(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2
    )
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    prompt = np.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], np.int32)
    full = _greedy(model, params, prompt, 8)
    quant = _greedy(model, params, prompt, 8, kv_dtype=jnp.int8)
    # Same shape always; token agreement nearly always — assert a high
    # overlap rather than exact equality to keep the test robust to the
    # quantization noise it exists to exercise.
    assert full.shape == quant.shape
    agree = (full == quant).mean()
    assert agree >= 0.8, f"int8-KV generation diverged: {agree:.0%} agreement"


# ---------------------------------------------------------------------------
# Paged (block-table) entry point — ISSUE 19
# ---------------------------------------------------------------------------

from distributed_machine_learning_tpu.ops.pallas.decode_attention import (  # noqa: E402
    paged_attention_reference,
    paged_flash_attention,
    paged_flash_qualifies,
)


def _paged_case(seed, W, nb, bs, H, Hkv, D, positions):
    """Build a pool + per-lane tables where each lane's logical blocks
    are scattered (non-contiguous, interleaved across lanes) physical
    blocks, with garbage in every slot a lane does not own."""
    rng = np.random.default_rng(seed)
    k_pool = jnp.asarray(rng.standard_normal((nb, Hkv, bs, D)), jnp.float32)
    v_pool = jnp.asarray(rng.standard_normal((nb, Hkv, bs, D)), jnp.float32)
    mb = max(p // bs + 1 for p in positions)
    perm = rng.permutation(nb)
    tables = np.zeros((W, mb), np.int32)
    take = 0
    for w, p in enumerate(positions):
        n = p // bs + 1
        tables[w, :n] = perm[take:take + n]
        take += n
    assert take <= nb, "case needs a bigger pool"
    q = jnp.asarray(rng.standard_normal((W, 1, H, D)), jnp.float32)
    return q, k_pool, v_pool, jnp.asarray(tables), jnp.asarray(
        positions, jnp.int32
    )


def test_paged_reference_matches_dense_cached_attention():
    """Lane-by-lane: gathering a lane's pages into a dense cache and
    running the einsum path gives the same output as the paged
    reference over the shared pool."""
    W, nb, bs, H, Hkv, D = 3, 24, 16, 8, 2, 32
    positions = [5, 40, 17]
    q, kp, vp, tbl, pos = _paged_case(0, W, nb, bs, H, Hkv, D, positions)
    out = paged_attention_reference(q, kp, vp, tbl, pos)
    for w, p in enumerate(positions):
        n = p // bs + 1
        k = kp[np.asarray(tbl)[w, :n]].transpose(1, 0, 2, 3).reshape(
            1, Hkv, n * bs, D
        )
        v = vp[np.asarray(tbl)[w, :n]].transpose(1, 0, 2, 3).reshape(
            1, Hkv, n * bs, D
        )
        ref = _cached_attention(
            q[w:w + 1], k, v, jnp.asarray([p], jnp.int32)
        )
        np.testing.assert_allclose(
            np.asarray(out[w:w + 1]), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


@pytest.mark.parametrize("positions", [[0, 0], [3, 90], [63, 64], [127, 1]])
def test_paged_kernel_matches_reference(positions):
    """The Pallas block-table kernel (interpret mode on CPU) against
    the XLA gather reference at ragged frontiers, including lanes at
    position 0 and lanes ending exactly on block boundaries."""
    W, nb, bs, H, Hkv, D = 2, 20, 16, 4, 2, 32
    q, kp, vp, tbl, pos = _paged_case(7, W, nb, bs, H, Hkv, D, positions)
    ref = paged_attention_reference(q, kp, vp, tbl, pos)
    out = paged_flash_attention(q, kp, vp, tbl, pos)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_paged_qualifies_rule():
    # Interpret mode is on for the CPU harness, so any block size
    # qualifies here; the 128-multiple rule is for real TPUs.
    assert paged_flash_qualifies(128)
    assert paged_flash_qualifies(512)
