"""Gang-wide observability plane (ISSUE 6): cross-rank metric
aggregation, the straggler detector (offline over metrics streams and
live over heartbeat snapshots), heartbeat enrichment, collision-safe
per-rank telemetry, the ``gang_status``/``trace_merge`` tools, and the
chaos proof — a 4-worker gang whose stalled rank is flagged by the
advisory detector *before* the peer-timeout abort tears the gang down.
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys
import time

import pytest

from distributed_machine_learning_tpu.runtime.coordinator import (
    GANG_HEALTH_FILE,
    GangCoordinator,
    append_health_event,
    clear_gang_state,
)
from distributed_machine_learning_tpu.runtime.faults import FaultEvents
from distributed_machine_learning_tpu.runtime.supervisor import (
    gang_supervise,
)
from distributed_machine_learning_tpu.telemetry import (
    MetricsRegistry,
    SpanTracer,
    Telemetry,
    aggregate_gang_metrics,
    discover_rank_streams,
    instance_file,
    read_jsonl,
)
from distributed_machine_learning_tpu.telemetry.aggregator import (
    HeartbeatSampler,
    StragglerDetector,
    publish_rollup,
    read_beats,
    read_health_events,
    serving_stage_samples,
)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_rows(path, rows):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "a") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")


def _row(step, iter_s, *, attempt=0, eps=100.0, **extra):
    return {"step": step, "iter_s": iter_s, "attempt": attempt,
            "examples_per_s": eps, "barrier_wait_s": iter_s * 0.25,
            "compute_s": iter_s * 0.75, **extra}


# ---------------------------------------------------------------------------
# Telemetry instance suffix: sink collision safety (satellite fix)
# ---------------------------------------------------------------------------


def test_instance_file_splices_tag():
    assert instance_file("metrics.jsonl", "rank3") == "metrics.rank3.jsonl"
    assert instance_file("trace.json", "rank0") == "trace.rank0.json"
    assert instance_file("metrics.jsonl", None) == "metrics.jsonl"
    with pytest.raises(ValueError):
        instance_file("metrics.jsonl", "a/b")


def test_shared_dir_instances_never_interleave(tmp_path):
    """Regression (satellite): two processes pointed at the SAME
    telemetry dir must land in separate streams — interleaved appends
    would weld rows into garbage.  With instance tags, each stream
    parses completely and carries only its own rows; the canonical
    single-process filenames are untouched."""
    tels = {r: Telemetry(tmp_path, instance=f"rank{r}", enabled=True)
            for r in (0, 1)}
    for step in range(30):
        for r, tel in tels.items():
            tel.log_step(step, iter_s=0.01 + r, rank=r)
    for r, tel in tels.items():
        tel.tracer.instant("gang_worker_finish", rank=r)
        tel.close()
    for r in (0, 1):
        path = tmp_path / f"metrics.rank{r}.jsonl"
        rows = read_jsonl(path)  # raises on any mid-file corruption
        assert len(rows) == 30
        assert all(row["rank"] == r for row in rows)
        assert (tmp_path / f"registry.rank{r}.json").exists()
        assert (tmp_path / f"trace.rank{r}.json").exists()
    assert not (tmp_path / "metrics.jsonl").exists()
    # Attempt numbering resumes per-instance, not from a neighbor.
    again = Telemetry(tmp_path, instance="rank1", enabled=True)
    assert again.attempt == 1
    again.close()


# ---------------------------------------------------------------------------
# Aggregator: discovery + cross-rank rollups
# ---------------------------------------------------------------------------


def test_discover_rank_streams_both_layouts(tmp_path):
    _write_rows(str(tmp_path / "metrics.rank0.jsonl"), [_row(0, 0.01)])
    _write_rows(str(tmp_path / "rank1" / "metrics.jsonl"),
                [_row(0, 0.01)])
    (tmp_path / "rank2").mkdir()  # no metrics: not a stream
    streams = discover_rank_streams(tmp_path)
    assert sorted(streams) == [0, 1]
    assert streams[0]["metrics"].endswith("metrics.rank0.jsonl")
    assert streams[1]["metrics"].endswith(os.path.join("rank1",
                                                       "metrics.jsonl"))
    assert discover_rank_streams(tmp_path / "nope") == {}


def test_aggregate_cross_rank_rollups(tmp_path):
    # Rank 2 runs 3x slower than ranks 0/1 on every step.
    for r in (0, 1, 2):
        speed = 0.03 if r == 2 else 0.01
        _write_rows(str(tmp_path / f"metrics.rank{r}.jsonl"),
                    [_row(s, speed, eps=1.0 / speed) for s in range(6)])
    rollup = aggregate_gang_metrics(tmp_path, multiple=2.0,
                                    consecutive=2)
    assert rollup.ranks == [0, 1, 2]
    assert len(rollup.steps) == 6
    step0 = rollup.steps[0]
    assert step0["iter_s"]["min"] == pytest.approx(0.01)
    assert step0["iter_s"]["median"] == pytest.approx(0.01)
    assert step0["iter_s"]["max"] == pytest.approx(0.03)
    assert step0["skew"] == pytest.approx(3.0)
    assert step0["phases"]["compute_s"]["max"] == pytest.approx(0.0225)
    assert step0["examples_per_s"]["2"] == pytest.approx(1 / 0.03)
    assert rollup.skew["p95"] == pytest.approx(3.0)
    # Offline detector: rank 2 is flagged once (one episode), with the
    # step of the verdict recorded.
    assert [v["rank"] for v in rollup.stragglers] == [2]
    assert rollup.stragglers[0]["ratio"] == pytest.approx(3.0)
    assert rollup.per_rank[2]["iter_s_mean"] == pytest.approx(0.03)
    assert rollup.per_rank[0]["rows"] == 6
    assert sorted(rollup.phases) == ["barrier_wait_s", "compute_s"]


def test_aggregate_last_attempt_wins_and_tolerates_torn_line(tmp_path):
    p = str(tmp_path / "metrics.rank0.jsonl")
    _write_rows(p, [_row(s, 0.01, attempt=0) for s in range(4)])
    # Attempt 1 replays steps 2..3 with different timings; its rows are
    # authoritative.  Warm-up rows never enter the rollup.
    _write_rows(p, [_row(2, 0.05, attempt=1),
                    _row(3, 0.05, attempt=1),
                    dict(_row(4, 9.9, attempt=1), warmup=True)])
    _write_rows(str(tmp_path / "metrics.rank1.jsonl"),
                [_row(s, 0.01, attempt=a)
                 for a, s in [(0, 0), (0, 1), (0, 2), (1, 2), (1, 3)]])
    with open(p, "a") as f:
        f.write('{"step": 5, "iter_s": 0.0')  # kill mid-write
    rollup = aggregate_gang_metrics(tmp_path)
    by_step = {e["step"]: e for e in rollup.steps}
    assert sorted(by_step) == [0, 1, 2, 3]  # warmup + torn rows dropped
    assert by_step[2]["iter_s"]["max"] == pytest.approx(0.05)
    assert by_step[2]["skew"] == pytest.approx(0.05 / 0.03)
    assert rollup.per_rank[0]["attempts"] == [0, 1]


def test_publish_rollup_mirrors_into_registry(tmp_path):
    for r in (0, 1, 2):
        _write_rows(str(tmp_path / f"metrics.rank{r}.jsonl"),
                    [_row(s, 0.09 if r == 1 else 0.01) for s in range(5)])
    rollup = aggregate_gang_metrics(tmp_path, multiple=3.0,
                                    consecutive=2)
    reg = MetricsRegistry()
    publish_rollup(rollup, reg)
    snap = reg.snapshot()
    counters = {(c["name"], c["labels"].get("rank")): c["value"]
                for c in snap["counters"]}
    assert counters[("gang_straggler", "1")] == 1
    gauges = {g["name"]: g["value"] for g in snap["gauges"]}
    assert gauges["gang_skew_ratio"] == pytest.approx(9.0)


# ---------------------------------------------------------------------------
# StragglerDetector semantics
# ---------------------------------------------------------------------------


def test_straggler_detector_flags_after_consecutive():
    d = StragglerDetector(multiple=3.0, consecutive=3)
    sample = {0: 0.01, 1: 0.01, 2: 0.01, 3: 0.2}
    assert d.update(sample) == []
    assert d.update(sample) == []
    verdicts = d.update(sample)
    assert [v.rank for v in verdicts] == [3]
    assert verdicts[0].ratio == pytest.approx(20.0)
    assert verdicts[0].streak == 3
    assert d.skew_ratio == pytest.approx(20.0)
    # Already flagged: the same episode never re-fires.
    assert d.update(sample) == []
    assert d.flags_total == 1


def test_straggler_detector_recovery_rearms():
    d = StragglerDetector(multiple=3.0, consecutive=2)
    slow = {0: 0.01, 1: 0.01, 2: 0.1}
    ok = {0: 0.01, 1: 0.01, 2: 0.01}
    d.update(slow)
    assert [v.rank for v in d.update(slow)] == [2]
    d.update(ok)  # recovery: unflag + streak reset
    assert 2 not in d.flagged
    d.update(slow)
    assert [v.rank for v in d.update(slow)] == [2]  # a NEW episode
    assert d.flags_total == 2


def test_straggler_detector_needs_a_gang():
    d = StragglerDetector(multiple=2.0, consecutive=1)
    assert d.update({0: 5.0}) == []          # one rank is not a gang
    assert d.update({0: 5.0, 1: None}) == []  # None = no timing yet
    assert d.update({0: 0.0, 1: 0.0}) == []   # zero median: no verdict
    with pytest.raises(ValueError):
        StragglerDetector(multiple=1.0)
    with pytest.raises(ValueError):
        StragglerDetector(consecutive=0)
    with pytest.raises(ValueError):
        StragglerDetector(min_ranks=1)


# ---------------------------------------------------------------------------
# Heartbeat enrichment + live sampling
# ---------------------------------------------------------------------------

HB = 0.05
TIMEOUT = 30.0  # generous: these tests never want a real abort


def test_heartbeat_carries_metric_snapshot(tmp_path):
    c = GangCoordinator(tmp_path, rank=0, world=2,
                        heartbeat_interval_s=HB, peer_timeout_s=TIMEOUT,
                        check_self=False, on_abort=lambda r: None,
                        metrics_window=4).start()
    try:
        for i in range(6):
            c.observe_step(i + 1, 0.02,
                           {"barrier_wait_s": 0.005, "compute_s": 0.015})
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            payload = read_beats(tmp_path).get(0)
            if payload and "metrics" in payload:
                break
            time.sleep(0.01)
        m = payload["metrics"]
        assert payload["step"] == 6
        assert m["steps_timed"] == 4  # window, not whole history
        assert m["step_time_s"] == pytest.approx(0.02)
        assert m["last_step_time_s"] == pytest.approx(0.02)
        assert m["phases"] == {"barrier_wait_s": 0.005,
                               "compute_s": 0.015}
    finally:
        c.stop()
    with pytest.raises(ValueError):
        GangCoordinator(tmp_path, rank=0, world=1, metrics_window=0)


def _beat(rank, step, *, beat_age=0.0, seq=1, step_time=None,
          suspended=False, done=False):
    payload = {"rank": rank, "seq": seq, "step": step,
               "beat_age": beat_age, "suspended": suspended,
               "done": done, "time": time.time()}
    if step_time is not None:
        payload["metrics"] = {"step_time_s": step_time,
                              "last_step_time_s": step_time,
                              "steps_timed": 4, "phases": {}}
    return payload


def _write_beats(gang_dir, payloads):
    os.makedirs(gang_dir, exist_ok=True)
    for p in payloads:
        with open(os.path.join(gang_dir, f"beat_rank{p['rank']}.json"),
                  "w") as f:
            json.dump(p, f)


def test_sampler_inflates_only_the_barrier_holder(tmp_path):
    """The attribution rule: in-flight time counts only against ranks
    at the gang's minimum published step (the ones the lock-step
    barrier waits on) — blocked-but-ahead ranks keep their rolling
    mean, so the median stays honest and the true straggler stands
    out."""
    gang = str(tmp_path)
    sampler = HeartbeatSampler()
    _write_beats(gang, [
        _beat(0, step=8, step_time=0.01),
        _beat(1, step=7, step_time=0.01),               # min: stalled
        _beat(2, step=8, step_time=0.01),
        _beat(3, step=9, step_time=0.01, suspended=True),
        _beat(4, step=12, step_time=0.01, done=True),
    ])
    sampler.sample(gang)           # first sight: seq baselines
    time.sleep(0.25)               # no beat rewrites: files frozen
    samples = sampler.sample(gang)
    assert samples[1].eff_step_time_s >= 0.25  # holder: age counts
    assert samples[0].eff_step_time_s == pytest.approx(0.01)
    assert samples[2].eff_step_time_s == pytest.approx(0.01)
    assert samples[3].eff_step_time_s == pytest.approx(0.01)  # suspended
    assert samples[4].done and samples[1].step == 7
    # Fed to the detector, only rank 1 crosses the threshold.
    d = StragglerDetector(multiple=4.0, consecutive=1)
    feed = {r: s.eff_step_time_s for r, s in samples.items()
            if not s.done and not s.suspended}
    assert [v.rank for v in d.update(feed)] == [1]


def test_sampler_no_timing_published_is_no_judgement(tmp_path):
    sampler = HeartbeatSampler()
    _write_beats(str(tmp_path), [_beat(0, step=0), _beat(1, step=0)])
    samples = sampler.sample(str(tmp_path))
    assert all(s.eff_step_time_s is None for s in samples.values())
    d = StragglerDetector(multiple=2.0, consecutive=1)
    assert d.update({r: s.eff_step_time_s
                     for r, s in samples.items()}) == []


# ---------------------------------------------------------------------------
# gang_supervise: live advisory (stub workers, no jax)
# ---------------------------------------------------------------------------


_STALL_STUB = """\
import os, sys, time
sys.path.insert(0, {repo!r})
from distributed_machine_learning_tpu.runtime.coordinator import (
    GangCoordinator,
)
rank, world = {rank}, {world}
gang = os.path.join({root!r}, "gang")
c = GangCoordinator(gang, rank=rank, world=world,
                    heartbeat_interval_s=0.05, peer_timeout_s=30.0,
                    check_self=False, on_abort=lambda r: None).start()
end = time.monotonic() + 2.0
step = 0
while time.monotonic() < end:
    if rank == 1 and step >= 3:
        time.sleep(0.05)   # stalled: progress frozen at step 3
        continue
    step += 1
    c.observe_step(step, 0.01)
    time.sleep(0.02)
c.finish()
"""


def test_gang_supervise_flags_live_straggler(tmp_path):
    """Three stub ranks heartbeat through a real gang dir; rank 1
    freezes its progress mid-run.  The supervisor's poll loop must flag
    it (events.stragglers, a gang_health.jsonl verdict keyed to the
    ORIGINAL rank) while the gang still finishes cleanly — advisory
    detection changes no policy."""

    def worker_cmd(rank, attempt, world, orig_rank):
        code = _STALL_STUB.format(repo=REPO, rank=rank, world=world,
                                  root=str(tmp_path))
        return [sys.executable, "-c", code]

    events = FaultEvents()
    codes = gang_supervise(
        worker_cmd, 3, tmp_path / "gang", events=events, poll_s=0.05,
        straggler_multiple=3.0, straggler_consecutive=2,
    )
    assert codes == [0, 0, 0]
    assert events.stragglers >= 1
    verdicts = [e for e in read_health_events(tmp_path / "gang")
                if e["kind"] == "straggler"]
    assert verdicts and all(v["rank"] == 1 for v in verdicts)
    assert verdicts[0]["ratio"] > 3.0
    assert events.gang_restarts == 0  # advisory only: no relaunch


def test_gang_supervise_records_restart_history(tmp_path):
    """The health ledger keeps the restart history the status tool
    renders — and a fresh supervision run starts it clean."""
    append_health_event(tmp_path / "gang", "straggler", rank=9)

    body = ("import sys\n"
            "sys.exit(7 if {attempt} == 0 and {rank} == 0 else 0)\n")

    def worker_cmd(rank, attempt, world, orig_rank):
        return [sys.executable, "-c",
                body.format(rank=rank, attempt=attempt)]

    events = FaultEvents()
    codes = gang_supervise(worker_cmd, 2, tmp_path / "gang",
                           events=events, poll_s=0.05, max_restarts=2)
    assert codes == [0, 0]
    health = read_health_events(tmp_path / "gang")
    assert all(e.get("rank") != 9 for e in health)  # stale run cleared
    restarts = [e for e in health if e["kind"] == "restart"]
    assert len(restarts) == 1 and restarts[0]["attempt"] == 1
    assert "exited 7" in restarts[0]["why"]
    assert events.stragglers == 0  # instant exits: nothing to judge


def test_clear_gang_state_groups_health_with_run_history(tmp_path):
    append_health_event(tmp_path, "straggler", rank=1)
    clear_gang_state(tmp_path)  # between attempts: history kept
    assert (tmp_path / GANG_HEALTH_FILE).exists()
    clear_gang_state(tmp_path, restore_records=True)  # fresh run
    assert not (tmp_path / GANG_HEALTH_FILE).exists()


# ---------------------------------------------------------------------------
# tools/gang_status.py + tools/trace_merge.py (stdlib CLIs)
# ---------------------------------------------------------------------------


def _synthetic_gang(tmp_path):
    gang = str(tmp_path / "gang")
    tel = os.path.join(gang, "telemetry")
    _write_beats(gang, [
        _beat(0, step=12, step_time=0.01, done=True),
        _beat(1, step=8, beat_age=55.0, step_time=0.04),
    ])
    # An OLD verdict (attempt 0, rank 0) must NOT flag the live table —
    # only the latest attempt's verdicts are current state, matched by
    # CURRENT rank numbering (cur_rank), not original identity.
    append_health_event(gang, "straggler", rank=0, cur_rank=0, attempt=0,
                        step=2, ratio=4.2, value_s=0.042,
                        median_s=0.01)
    append_health_event(gang, "restart", attempt=1, world=2,
                        why="rank 1 exited 21")
    append_health_event(gang, "shrink", attempt=2, from_world=2,
                        to_world=1, lost=[3], restore_step=5)
    append_health_event(gang, "straggler", rank=2, cur_rank=1, attempt=2,
                        step=8, ratio=5.5, value_s=0.055,
                        median_s=0.01)
    with open(os.path.join(gang, "faults_fired.jsonl"), "w") as f:
        f.write(json.dumps({"index": 0, "kind": "kill_rank", "at": 7,
                            "rank": 1}) + "\n")
    for r in (0, 1):
        _write_rows(os.path.join(tel, f"metrics.rank{r}.jsonl"),
                    [_row(s, 0.04 if r else 0.01) for s in range(5)])
    return gang


def test_gang_status_tool_renders_and_dumps(tmp_path, capsys):
    tool = _load_tool("gang_status")
    gang = _synthetic_gang(tmp_path)
    assert tool.main([gang]) == 0
    out = capsys.readouterr().out
    assert "2 rank(s) heartbeating" in out
    assert "DONE" in out and "STRAGGLER" in out
    assert "straggler: rank 2 at step 8" in out  # history: orig ids
    assert "restart #1" in out and "rank 1 exited 21" in out
    assert "shrink @attempt 2: 2 -> 1" in out
    assert "fault fired: kill_rank rank 1" in out
    assert "skew" in out and "rank 0: 5 step row(s)" in out
    assert tool.main([gang, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["world"] == 2
    # Live flag: latest attempt's verdict, keyed by CURRENT rank —
    # cur rank 1 (orig 2) is flagged, and the stale attempt-0 verdict
    # against rank 0 is history, not state.
    assert payload["ranks"][1]["straggler"] is True
    assert payload["ranks"][0]["straggler"] is False
    # Two ranks: the median is the midpoint of (0.01, 0.04), so the
    # skew ratio is 0.04 / 0.025.
    assert payload["rollup"]["skew"]["max"] == pytest.approx(1.6)
    assert tool.main([str(tmp_path / "missing")]) == 2


def test_trace_merge_fuses_one_track_per_rank(tmp_path, capsys):
    tel = tmp_path / "tel"
    tel.mkdir()
    tr = SpanTracer(tel / "trace.rank0.json", enabled=True)
    t0 = tr.now()
    tr.complete("compute", t0, t0 + 0.01, step=0)
    tr.instant("gang_worker_start", attempt=0, rank=0)
    tr.close()
    # Rank 1 died mid-write: unterminated array + torn final event.
    (tel / "trace.rank1.json").write_text(
        '[\n{"name": "barrier_wait", "ph": "X", "ts": 5.0, "dur": 2.0,'
        ' "pid": 0, "tid": 9},\n{"name": "torn_ev'
    )
    tool = _load_tool("trace_merge")
    assert tool.main([str(tel)]) == 0
    out = capsys.readouterr().out
    assert "2 stream(s)" in out and "rank1:1" in out
    with open(tel / "trace.merged.json") as f:
        merged = json.load(f)  # strictly-valid JSON, always
    events = merged["traceEvents"]
    assert {e["pid"] for e in events} == {0, 1}
    names = {(e["pid"], e["name"]) for e in events}
    assert (1, "barrier_wait") in names  # re-homed from its local pid 0
    assert (1, "torn_ev") not in names and (1, "torn_ev'") not in names
    meta = {e["pid"]: e["args"]["name"] for e in events
            if e.get("ph") == "M" and e["name"] == "process_name"}
    assert meta == {0: "rank 0", 1: "rank 1"}
    # An empty dir is an explicit error, not an empty timeline.
    empty = tmp_path / "empty"
    empty.mkdir()
    assert tool.main([str(empty)]) == 2


def test_trace_merge_rehomes_serving_streams_with_flow_links(
        tmp_path, capsys):
    """ISSUE 17: serving streams (``trace.router.json`` /
    ``trace.replica<r>.json``) are re-homed above
    :data:`SERVING_PID_BASE` so they can never collide with rank
    tracks, and ``request`` spans sharing a rid across processes are
    stitched with flow arrows — one ``s`` + one ``f`` per rid that
    actually crosses a pid boundary."""
    tel = tmp_path / "tel"
    tel.mkdir()

    def _stream(name, spans):
        tr = SpanTracer(tel / name, enabled=True)
        t0 = tr.now()
        for i, (sname, args) in enumerate(spans):
            tr.complete(sname, t0 + i * 0.01, t0 + i * 0.01 + 0.005,
                        **args)
        tr.close()

    _stream("trace.rank0.json", [("compute", {"step": 0})])
    _stream("trace.router.json", [
        ("request", {"rid": "r1"}),
        ("request", {"rid": "r2"}),
        ("request", {"rid": "solo"}),   # router-only: no flow link
    ])
    _stream("trace.replica0.json", [
        ("request", {"rid": "r1", "rank": 0, "stage": "posted"}),
    ])
    _stream("trace.replica1.json", [
        ("request", {"rid": "r2", "rank": 1, "stage": "posted"}),
    ])

    tool = _load_tool("trace_merge")
    assert tool.main([str(tel)]) == 0
    out = capsys.readouterr().out
    assert "4 stream(s)" in out
    assert "router:3" in out and "replica0:1" in out

    with open(tel / "trace.merged.json") as f:
        merged = json.load(f)
    events = merged["traceEvents"]
    base = tool.SERVING_PID_BASE
    real = [e for e in events
            if e.get("ph") != "M" and e.get("name") != "request_flow"]
    assert {e["pid"] for e in real} == {0, base, base + 1, base + 2}
    meta = {e["pid"]: e["args"]["name"] for e in events
            if e.get("ph") == "M" and e["name"] == "process_name"}
    assert meta == {0: "rank 0", base: "serve router",
                    base + 1: "serve replica 0",
                    base + 2: "serve replica 1"}

    flows = [e for e in events if e.get("name") == "request_flow"]
    assert len(flows) == 4                       # 2 rids x (s + f)
    by_id = {}
    for e in flows:
        by_id.setdefault(e["id"], []).append(e)
    assert len(by_id) == 2                       # "solo" got no link
    for pair in by_id.values():
        assert sorted(e["ph"] for e in pair) == ["f", "s"]
        assert len({e["pid"] for e in pair}) == 2
        assert all(e["pid"] >= base for e in pair)


def test_serving_stage_samples_feed_the_straggler_detector():
    """ISSUE 17 satellite: the aggregator derives per-replica compute
    durations straight from the request event stream — last sample per
    rank wins, non-replica actors and malformed events are ignored."""
    events = [
        {"stage": "computed", "by": "replica0", "dt": 0.01},
        {"stage": "computed", "by": "replica2", "dt": 0.05},
        {"stage": "computed", "by": "replica2", "dt": 0.07},  # last wins
        {"stage": "bound", "by": "replica1", "dt": 0.5},      # wrong stage
        {"stage": "computed", "by": "router", "dt": 0.02},    # not a replica
        {"stage": "computed", "by": "replica3", "dt": None},  # no duration
        "garbage",
    ]
    assert serving_stage_samples(events) == {0: 0.01, 2: 0.07}
    assert serving_stage_samples(None) == {}
    assert serving_stage_samples(events, stage="bound") == {1: 0.5}


def test_trace_summary_counts_instants(tmp_path):
    """Satellite fix: trace instants (fault/shrink markers) used to be
    silently dropped; they now land in the per-phase table."""
    tr = SpanTracer(tmp_path / "trace.json", enabled=True)
    t0 = tr.now()
    tr.complete("data_wait", t0, t0 + 0.01)
    tr.instant("fault_rank_stalls")
    tr.instant("gang_shrink", from_world=4, to_world=3)
    tr.instant("gang_shrink", from_world=3, to_world=2)
    tr.close()
    tool = _load_tool("trace_summary")
    out = tool.summarize(str(tmp_path))
    assert "gang_shrink" in out and "(2 instant(s))" in out
    assert "fault_rank_stalls" in out and "(1 instant(s))" in out


# ---------------------------------------------------------------------------
# Chaos: the stalled rank is flagged BEFORE the peer-timeout abort
# ---------------------------------------------------------------------------


def _run_gang(root, *, faults=None, workers=4, steps=12, save_every=5,
              peer_timeout=4.0, timeout=280):
    from distributed_machine_learning_tpu.cli.gang import (
        scrubbed_worker_env,
    )

    cmd = [
        sys.executable, "-m", "distributed_machine_learning_tpu.cli.gang",
        "--workers", str(workers), "--steps", str(steps),
        "--save-every", str(save_every),
        "--ckpt-dir", os.path.join(root, "ckpt"),
        "--gang-dir", os.path.join(root, "gang"),
        "--peer-timeout", str(peer_timeout),
    ]
    if faults:
        cmd += ["--faults", faults]
    return subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout,
        env=scrubbed_worker_env(REPO), cwd=REPO,
    )


@pytest.mark.slow
@pytest.mark.faultinject
def test_gang_chaos_straggler_flagged_before_abort(tmp_path):
    """ISSUE 6's acceptance bar.  stall_rank@1:7:30 on a 4-worker gang:
    rank 1 wedges before step 7 and the stall exceeds the 1.5x
    peer-timeout budget, so the gang eventually aborts and restarts —
    but the advisory detector must name rank 1 FIRST, the verdict must
    land in the default-on telemetry plane (gang_straggler{rank=1},
    gang_skew_ratio, FaultEvents.stragglers -> resilience_summary,
    gang_health.jsonl), gang_status must render the story from the gang
    dir alone, and trace_merge must fuse one Perfetto timeline with a
    track per rank spanning both attempts."""
    root = str(tmp_path / "chaos")
    res = _run_gang(root, faults="stall_rank@1:7:30")
    assert res.returncode == 0, res.stdout + res.stderr

    # Flagged before the abort tore the gang down: the advisory line
    # precedes the coordinated-restart line in the supervisor log.
    flag_at = res.stdout.find("straggler advisory: rank 1")
    restart_at = res.stdout.find("coordinated restart")
    assert flag_at != -1, res.stdout
    assert restart_at != -1, res.stdout
    assert flag_at < restart_at, res.stdout
    assert "straggler advisories (slow ranks)" in res.stdout  # summary
    assert "cross-rank step-time skew" in res.stdout

    gang = os.path.join(root, "gang")
    tel = os.path.join(gang, "telemetry")

    # The default-on telemetry plane: supervisor registry carries the
    # verdict counters and the skew gauge.
    with open(os.path.join(tel, "registry.json")) as f:
        snap = json.load(f)
    counters = {(c["name"], c["labels"].get("rank", c["labels"].get(
        "kind"))): c["value"] for c in snap["counters"]}
    assert counters[("gang_straggler", "1")] >= 1
    assert counters[("fault_events", "stragglers")] >= 1
    assert counters[("gang_restarts", None)] >= 1
    # The gauge is LIVE (last write wins): after the healthy restart it
    # reads near 1; the episode's peak ratio is in the health verdicts.
    gauges = {g["name"]: g["value"] for g in snap["gauges"]}
    assert gauges["gang_skew_ratio"] > 0.0

    # The health ledger tells the same story, keyed to the rank.
    health = read_health_events(gang)
    verdicts = [e for e in health if e["kind"] == "straggler"]
    assert verdicts and all(v["rank"] == 1 for v in verdicts)
    assert any(e["kind"] == "restart" for e in health)

    # Every rank streamed collision-safe metrics; the restarted attempt
    # APPENDED to the same per-rank streams (attempt tags 0 and 1).
    rollup = aggregate_gang_metrics(tel)
    assert rollup.ranks == [0, 1, 2, 3]
    assert rollup.per_rank[0]["attempts"] == [0, 1]
    assert rollup.per_rank[0]["last_step"] == 11

    # gang_status renders the per-rank table + history from the dirs.
    status = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "gang_status.py"),
         gang], capture_output=True, text=True, timeout=60,
    )
    assert status.returncode == 0, status.stdout + status.stderr
    assert "4 rank(s) heartbeating" in status.stdout
    assert "straggler: rank 1" in status.stdout
    assert "restart #1" in status.stdout
    assert "Cross-rank rollup" in status.stdout

    # trace_merge: one Perfetto-loadable timeline, a track per rank,
    # spanning both attempts.
    merge = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_merge.py"),
         tel], capture_output=True, text=True, timeout=60,
    )
    assert merge.returncode == 0, merge.stdout + merge.stderr
    with open(os.path.join(tel, "trace.merged.json")) as f:
        merged = json.load(f)
    events = merged["traceEvents"]
    assert {e["pid"] for e in events
            if e.get("ph") != "M"} == {0, 1, 2, 3}
    starts = [e for e in events if e["name"] == "gang_worker_start"]
    attempts = {e["args"]["attempt"] for e in starts}
    assert attempts == {0, 1}, starts
    names = {e["pid"]: e["args"]["name"] for e in events
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert names == {r: f"rank {r}" for r in range(4)}
