# dmlcheck-virtual-path: distributed_machine_learning_tpu/runtime/fixture.py
"""DML002 firing case: ledger appends missing fsync (and flush)."""
import json


def mark_fired(ledger_path, entry):
    with open(ledger_path, "a") as f:      # 'ledger' token, no fsync
        f.write(json.dumps(entry) + "\n")
        f.flush()


def record_health(gang_dir, payload):
    with open(gang_dir + "/gang_health.jsonl", "a") as f:  # neither
        f.write(json.dumps(payload) + "\n")
