"""Topology-aware hierarchical collectives (round 11): the 2-D ring
(``ops/topology.py``) — equivalence vs the flat ring and psum across
factored worlds, rank-identity under lossy codecs, the 2-D residual
invariant, the halving-doubling latency path, the auto-selector, and
the ``--ring-topology`` flag/validation surface."""

import contextlib
import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from conftest import shard_map_compat as shard_map

from distributed_machine_learning_tpu.ops.ring import (
    get_wire_scheme,
    ring_all_reduce_flat,
    ring_wire_bytes,
    ring_wire_bytes_by_axis,
)
from distributed_machine_learning_tpu.ops.topology import (
    HD_LOSSY_MAX_BYTES,
    Topology,
    halving_doubling_all_reduce_flat,
    hierarchical_all_reduce_flat,
    parse_topology,
    topology_all_reduce_flat,
    topology_wire_bytes,
)


def _run(n, fn, data, nout=1):
    """shard_map a per-device fn over stacked [n, ...] inputs."""
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    mesh = make_mesh(n)
    out_specs = P("batch") if nout == 1 else (P("batch"),) * nout
    f = shard_map(fn, mesh=mesh, in_specs=P("batch"), out_specs=out_specs,
                  check_vma=False)
    return jax.jit(f)(jnp.asarray(data))


# ---------------------------------------------------------------------------
# Descriptor surface: parsing, validation, selection.
# ---------------------------------------------------------------------------


def test_parse_topology_spec():
    assert parse_topology("2x4") == (2, 4)
    assert parse_topology("2×4") == (2, 4)
    assert parse_topology(" 8X1 ") == (8, 1)
    for bad in ("", "2x", "x4", "0x4", "2x0", "axb", "2x4x2", None):
        with pytest.raises(ValueError):
            parse_topology(bad)


def test_topology_descriptor_validation():
    t = Topology(2, 4, outer_scheme="int8")
    assert t.world == 8
    assert t.axis_scheme("outer").name == "int8"
    assert t.axis_scheme("inner").name == "none"
    with pytest.raises(ValueError, match="axes"):
        Topology(0, 4)
    with pytest.raises(ValueError, match="scheme"):
        Topology(2, 4, outer_scheme="fp4")


def test_selector_policy():
    """Round 20: the selector is prediction-driven — plans are priced
    through ``plan_hops`` × ``LinkModel`` and the cheapest wins, so the
    hd/hier crossover MOVES with the topology instead of sitting at a
    frozen 64 KiB."""
    from distributed_machine_learning_tpu.ops.topology import (
        DEFAULT_LINK_MODEL,
        LinkModel,
    )

    t = Topology(2, 4)  # exact both axes, world 8 (pow2)
    assert t.hd_max_bytes is None          # no byte threshold anymore
    assert t.select(1024) == "hd"          # small bucket → latency path
    # Analytic 2x4 crossover: hd trades hier's two extra outer
    # overheads for distance-multiplied outer bytes (B/4 extra), so hd
    # wins exactly below 8 · outer_overhead · outer_bandwidth.
    lm = DEFAULT_LINK_MODEL
    xover = 8 * lm.outer_overhead_s * lm.outer_bytes_per_s
    assert t.select(int(xover) - 4096) == "hd"
    assert t.select(int(xover) + 4096) == "hier"
    assert t.select(25 * 2**20) == "hier"
    assert (t.predict_bucket_time(25 * 2**20, "hier")
            < t.predict_bucket_time(25 * 2**20, "hd"))
    # 4x2 crossover is an INNER-axis property (the long hd exchange is
    # intra-node there): 4 · inner_overhead · inner_bandwidth.
    t42 = Topology(4, 2)
    xover42 = 4 * lm.inner_overhead_s * lm.inner_bytes_per_s
    assert t42.select(int(xover42) - 4096) == "hd"
    assert t42.select(int(xover42) + 4096) == "hier"
    # A custom link model moves the decision — no frozen constants.
    slow_outer = LinkModel(outer_overhead_s=100e-6)
    assert t.select(int(xover) + 4096, link=slow_outer) == "hd"
    # Flat never beats hier on a real hierarchy (more serial outer
    # overheads AND inner-times the outer bytes).
    assert (t.predict_bucket_time(1 << 20, "hier")
            < t.predict_bucket_time(1 << 20, "flat"))
    # A requested codec is only discarded for TRULY tiny buckets — the
    # fidelity bound survives the cost-model rewrite unchanged.
    tc = Topology(2, 4, outer_scheme="int8")
    assert tc.select(HD_LOSSY_MAX_BYTES) == "hd"
    assert tc.select(HD_LOSSY_MAX_BYTES + 1) == "hier"
    # hd_max_bytes: 0 still disables hd; a value still caps it.
    assert Topology(2, 4, hd_max_bytes=0).select(1024) == "hier"
    assert Topology(2, 4, hd_max_bytes=512).select(1024) == "hier"
    # Degenerate axes: flat ring, never a crash.
    assert Topology(1, 8).select(25 * 2**20) == "flat"
    assert Topology(8, 1).select(25 * 2**20) == "flat"
    # Non-power-of-two world: no hd path (6 = 2x3).
    assert Topology(2, 3).select(64) == "hier"
    assert Topology(1, 1).select(64) == "flat"


# ---------------------------------------------------------------------------
# Hierarchical all-reduce: equivalence + rank identity.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("world,inner,outer",
                         [(4, 2, 2), (8, 2, 4), (8, 4, 2)])
def test_hier_matches_pmean_and_rank_identical(world, inner, outer, rng):
    """Exact hierarchical == lax.pmean across every factored world, and
    every rank ends with identical bits (the chunks are relayed
    verbatim down the inner axis)."""
    topo = Topology(inner, outer)
    data = rng.standard_normal((world, 1000)).astype(np.float32)

    def per_device(x):
        x = x.reshape(-1)
        ours = hierarchical_all_reduce_flat(x, "batch", topo, mean=True)
        theirs = lax.pmean(x, "batch")
        return ours[None], (ours - theirs)[None]

    out, diff = _run(world, per_device, data, nout=2)
    np.testing.assert_allclose(np.asarray(diff), 0.0, atol=1e-5)
    out = np.asarray(out)
    for d in range(1, world):
        np.testing.assert_array_equal(out[d], out[0])


def test_hier_bitwise_equals_flat_for_exact_scheme(rng):
    """ISSUE acceptance: hierarchical ≡ flat BIT-FOR-BIT for the exact
    scheme.  Summation association differs between the plans, so the
    property is asserted on integer-valued gradients, where every
    partial sum is exactly representable and association cannot change
    the bits — the regime where 'bitwise' is a meaningful contract."""
    n = 8
    topo = Topology(2, 4)
    data = rng.integers(-8, 8, (n, 300)).astype(np.float32)
    hier = _run(n, lambda x: hierarchical_all_reduce_flat(
        x.reshape(-1), "batch", topo, mean=True)[None], data)
    flat = _run(n, lambda x: ring_all_reduce_flat(
        x.reshape(-1), "batch", n, mean=True)[None], data)
    np.testing.assert_array_equal(np.asarray(hier), np.asarray(flat))


@pytest.mark.parametrize("world,inner,outer,scheme",
                         [(8, 2, 4, "int8"), (8, 4, 2, "topk"),
                          (4, 2, 2, "int8")])
def test_hier_lossy_outer_rank_identical_and_bounded(world, inner, outer,
                                                     scheme, rng):
    """Lossy outer codec: all ranks END WITH IDENTICAL BITS (encoded
    payloads relayed verbatim through both gather phases) and the value
    stays within accumulated quantization error of the exact mean —
    replicated params cannot drift under the hierarchical plan."""
    topo = Topology(inner, outer, outer_scheme=scheme, topk_frac=1.0)
    data = rng.standard_normal((world, 513)).astype(np.float32)
    out = np.asarray(_run(world, lambda x: hierarchical_all_reduce_flat(
        x.reshape(-1), "batch", topo, mean=True)[None], data))
    for d in range(1, world):
        np.testing.assert_array_equal(out[d], out[0])
    exact = data.sum(axis=0) / world
    tol = 0.05 if scheme == "int8" else 1e-4  # topk@frac=1 sends all
    assert np.max(np.abs(out[0] - exact)) <= tol


@pytest.mark.parametrize("schemes", [
    {"outer_scheme": "int8"},
    {"inner_scheme": "int8", "outer_scheme": "int8"},
    {"inner_scheme": "topk", "outer_scheme": "int8"},
])
def test_hier_residual_accounts_total_dropped_mass(schemes, rng):
    """The 2-D residual invariant (ISSUE satellite): with codecs on the
    outer axis, both axes, or mixed, the per-axis residuals summed over
    ALL ranks equal N × (exact mean − output) — every dropped byte
    lands in exactly one rank's residual: inner reduce-scatter send
    errors, the outer sub-ring's own EF bookkeeping, and the
    inner-gather broadcast gap × inner at each node's owner."""
    n, L = 4, 192
    topo = Topology(2, 2, topk_frac=0.2, **schemes)
    data = rng.standard_normal((n, L)).astype(np.float32)

    def per_device(v):
        out, res = hierarchical_all_reduce_flat(
            v.reshape(-1), "batch", topo, mean=True, return_residual=True
        )
        return out[None], res[None]

    out, res = _run(n, per_device, data, nout=2)
    out, res = np.asarray(out), np.asarray(res)
    exact_mean = data.sum(axis=0) / n
    np.testing.assert_allclose(
        res.sum(axis=0), n * (exact_mean - out[0]), rtol=1e-4, atol=1e-4
    )


# ---------------------------------------------------------------------------
# Halving-doubling latency path.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("world", [4, 8])
def test_halving_doubling_matches_pmean_and_rank_identical(world, rng):
    data = rng.standard_normal((world, 999)).astype(np.float32)

    def per_device(x):
        x = x.reshape(-1)
        ours = halving_doubling_all_reduce_flat(x, "batch", world,
                                                mean=True)
        return ours[None], (ours - lax.pmean(x, "batch"))[None]

    out, diff = _run(world, per_device, data, nout=2)
    np.testing.assert_allclose(np.asarray(diff), 0.0, atol=1e-5)
    out = np.asarray(out)
    # Each chunk's sum is computed once at its owner and broadcast
    # verbatim: bitwise rank identity.
    for d in range(1, world):
        np.testing.assert_array_equal(out[d], out[0])


def test_halving_doubling_rejects_non_power_of_two():
    with pytest.raises(ValueError, match="power-of-two"):
        halving_doubling_all_reduce_flat(
            jnp.zeros((12,)), "batch", 6, mean=True
        )


# ---------------------------------------------------------------------------
# Degenerate topologies (the bugfix satellite): 1-sized axis == flat.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("spec,scheme_axis", [((1, 8), "outer"),
                                              ((8, 1), "inner")])
def test_degenerate_axis_is_flat_ring(spec, scheme_axis, rng):
    """``--ring-topology 1x8`` / ``8x1`` must degenerate to exactly the
    round-7 flat compressed ring — bit-for-bit, with the live axis's
    codec — not crash."""
    inner, outer = spec
    topo = Topology(inner, outer, hd_max_bytes=0,
                    **{f"{scheme_axis}_scheme": "int8"})
    n = 8
    data = rng.standard_normal((n, 100)).astype(np.float32)
    a = _run(n, lambda x: topology_all_reduce_flat(
        x.reshape(-1), "batch", topo, mean=True)[None], data)
    b = _run(n, lambda x: ring_all_reduce_flat(
        x.reshape(-1), "batch", n, mean=True,
        scheme=get_wire_scheme("int8"))[None], data)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# Static per-axis wire accounting (host arithmetic, no compiles).
# ---------------------------------------------------------------------------


def test_topology_wire_bytes_static():
    """Hand-checked per-axis accounting: the hierarchical plan's inner
    axis carries 2·(inner−1) hops of L/inner; the outer axis
    2·(outer−1) hops of L/(inner·outer) through the outer codec; a
    flat plan's bytes land on the outer axis (any hop crosses nodes);
    halving-doubling splits by exchange distance."""
    L, bb = 4096, 8192  # two 2048-elem buckets (8192 B of fp32 each)
    topo = Topology(2, 4, hd_max_bytes=0)
    got = topology_wire_bytes(L, topo, bucket_bytes=bb)
    assert got == {"inner": 2 * (2 * 1 * 1024 * 4),
                   "outer": 2 * (2 * 3 * 256 * 4)}
    # int8 outer: chunk + 4 scale bytes per hop, per bucket.
    topo8 = Topology(2, 4, outer_scheme="int8", hd_max_bytes=0)
    assert topology_wire_bytes(L, topo8, bucket_bytes=bb)["outer"] \
        == 2 * (2 * 3 * (256 + 4))
    # The flat plan under a 2-D topology: ALL bytes are inter-node
    # exposure (the block-edge ranks push every hop across nodes).
    flat = Topology(1, 8, hd_max_bytes=0)
    assert topology_wire_bytes(L, flat, bucket_bytes=bb) == {
        "inner": 0, "outer": ring_wire_bytes(L, 8, bucket_bytes=bb)}
    one_node = Topology(8, 1, hd_max_bytes=0)
    assert topology_wire_bytes(L, one_node, bucket_bytes=bb) == {
        "inner": ring_wire_bytes(L, 8, bucket_bytes=bb), "outer": 0}
    # hd (2x4, chunk=64 elems): distance-1 exchanges stay inside the
    # 2-wide blocks (inner); distances 2 and 4 cross (outer).
    hd = Topology(2, 4, hd_max_bytes=1 << 30)
    got = topology_wire_bytes(512, hd, bucket_bytes=bb)
    assert got == {"inner": 2 * 4 * 64 * 4,
                   "outer": 2 * (2 + 1) * 64 * 4}
    # ring_wire_bytes(topology=...) is the sum of the axes; the by-axis
    # helper without a topology keeps the flat label.
    assert ring_wire_bytes(L, 8, bucket_bytes=bb, topology=topo) \
        == sum(topology_wire_bytes(L, topo, bucket_bytes=bb).values())
    assert ring_wire_bytes_by_axis(L, 8, bucket_bytes=bb) == {
        "flat": ring_wire_bytes(L, 8, bucket_bytes=bb)}


# ---------------------------------------------------------------------------
# Strategy + CLI surface.
# ---------------------------------------------------------------------------


def test_ring_strategy_topology_validation():
    from distributed_machine_learning_tpu.parallel.strategies import (
        get_strategy,
    )

    with pytest.raises(ValueError, match="INNERxOUTER"):
        get_strategy("ring", topology="garbage")
    s = get_strategy("ring", compress="int8", topology="2x4")
    assert s.stateful  # EF protocol unchanged under a topology
    with pytest.raises(ValueError, match="must equal the mesh"):
        s.topology_for(6)
    topo = s.topology_for(8)
    assert (topo.inner, topo.outer) == (2, 4)
    assert topo.outer_scheme == "int8"  # --ring-compress maps to OUTER
    assert topo.inner_scheme == "none"
    # Per-axis accounting surface the telemetry counters consume.
    by_axis = s.wire_bytes_by_axis(100_000, 8)
    assert set(by_axis) == {"inner", "outer"}
    assert s.wire_bytes_per_step(100_000, 8) == sum(by_axis.values())
    flat = get_strategy("ring")
    assert set(flat.wire_bytes_by_axis(100_000, 8)) == {"flat"}


def test_cli_ring_topology_flag():
    """Bugfix satellite: invalid factorizations die at PARSE time with
    a flag-level message; the world-equality half fails before any
    training once the mesh is known (topology_for)."""
    from distributed_machine_learning_tpu.cli.common import (
        make_flag_parser,
        parse_flags,
    )

    parser = make_flag_parser("test")
    args = parse_flags(parser, ["--ring-topology", "2x4"])
    assert args.ring_topology == "2x4"
    assert parse_flags(parser, []).ring_topology is None
    for bad in ("2x", "0x4", "x", "2x4x2"):
        with pytest.raises(SystemExit), \
                contextlib.redirect_stderr(io.StringIO()):
            parse_flags(parser, ["--ring-topology", bad])


def test_train_step_hier_int8_ef_threads_residual(mesh8, rng):
    """The full vertical: make_train_step with the topology-aware
    int8+EF ring keeps the (state, x, y) signature, threads the donated
    per-device residual, and the residual is NONZERO (the lossy outer
    ring ran — the selector did not silently reroute the whole gradient
    down the exact latency path)."""
    from distributed_machine_learning_tpu.cli.common import (
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.models.registry import get_model
    from distributed_machine_learning_tpu.parallel.strategies import (
        get_strategy,
    )
    from distributed_machine_learning_tpu.train.sgd import SGDConfig
    from distributed_machine_learning_tpu.train.step import (
        make_train_step,
        shard_batch,
    )

    model = get_model("vggtest", use_bn=False)
    strategy = get_strategy("ring", compress="int8", topology="2x4")
    state = init_model_and_state(
        model, config=SGDConfig(learning_rate=0.1, weight_decay=0.0)
    )
    step = make_train_step(model, strategy, mesh=mesh8, augment=False)
    for _ in range(2):
        x = rng.integers(0, 256, (32, 32, 32, 3), dtype=np.uint8)
        y = rng.integers(0, 10, 32).astype(np.int32)
        state, loss = step(state, *shard_batch(mesh8, x, y))
    assert np.isfinite(float(loss))
    res = step.sync_state()
    leaves = jax.tree_util.tree_leaves(res)
    assert leaves and leaves[0].shape[0] == 8
    assert any(float(jnp.max(jnp.abs(l))) > 0 for l in leaves)
    for p in jax.tree_util.tree_leaves(state.params):
        assert bool(jnp.all(jnp.isfinite(p)))


# ---------------------------------------------------------------------------
# Acceptance: 40-iter fixed-seed parity (slow).
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_hier_int8_ef_acceptance_parity(mesh8, rng):
    """Round-11 acceptance: over the 40-iteration fixed-seed protocol,
    the hierarchical int8+EF ring's final loss is within 1% relative of
    the exact FLAT ring's — compression moved to the multi-hop plan
    without moving the trajectory."""
    from distributed_machine_learning_tpu.cli.common import (
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.models.registry import get_model
    from distributed_machine_learning_tpu.parallel.strategies import (
        get_strategy,
    )
    from distributed_machine_learning_tpu.train.sgd import SGDConfig
    from distributed_machine_learning_tpu.train.step import (
        make_train_step,
        shard_batch,
    )

    model = get_model("vggtest", use_bn=False)
    batches = [
        (rng.integers(0, 256, (64, 32, 32, 3), dtype=np.uint8),
         rng.integers(0, 10, 64).astype(np.int32))
        for _ in range(40)
    ]

    def final_loss(strategy):
        state = init_model_and_state(
            model, config=SGDConfig(learning_rate=0.1, weight_decay=0.0)
        )
        step = make_train_step(model, strategy, mesh=mesh8, augment=False)
        loss = None
        for x, y in batches:
            state, loss = step(state, *shard_batch(mesh8, x, y))
        return float(loss)

    exact = final_loss(get_strategy("ring"))
    hier = final_loss(
        get_strategy("ring", compress="int8", topology="2x4")
    )
    rel = abs(hier - exact) / abs(exact)
    assert rel <= 0.01, (hier, exact, rel)
