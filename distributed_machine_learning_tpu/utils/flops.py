"""Model-FLOPs estimates so every benchmark number carries an MFU.

The reference reports raw wall-clock only (group25.pdf §6); an MFU line
turns a throughput number into a statement about how much of the chip it
uses — the difference between "fast" and "done".  Estimates follow the
standard accounting: a training step costs ~3× the forward pass (forward
+ backward w.r.t. inputs + backward w.r.t. weights); matmul/conv FLOPs
count multiply and add separately (factor 2).
"""

from __future__ import annotations

# bf16 peak of the attached chip class (TPU v5 lite — docs/PERF.md).
# Overridable per call: MFU against the wrong peak is worse than no MFU.
DEFAULT_PEAK_TFLOPS = 197.0


def vgg_forward_flops_per_image(
    cfg: list, image_hw: int = 32, in_channels: int = 3,
    num_classes: int = 10, kernel: int = 3,
) -> float:
    """Forward FLOPs/image for a reference-style VGG cfg list
    (ints = conv out-channels, 'M' = 2×2 max-pool halving the spatial dim
    — models/vgg.py:_cfg ≡ part1/model.py:3-8)."""
    hw = image_hw
    cin = in_channels
    total = 0.0
    for item in cfg:
        if item == "M":
            hw //= 2
            continue
        total += 2.0 * hw * hw * cin * item * kernel * kernel
        cin = item
    total += 2.0 * cin * num_classes  # the Linear(512, 10) head
    return total


def vgg_train_flops_per_image(cfg: list, **kw) -> float:
    return 3.0 * vgg_forward_flops_per_image(cfg, **kw)


def transformer_train_flops_per_token(
    n_params: int, n_layers: int, d_model: int, seq_len: int,
    causal: bool = True,
) -> float:
    """~6·P per token for the matmuls (fwd 2P + bwd 4P) plus the
    attention score/value matmuls: 12·L·d·T per token fwd+bwd
    (2 matmuls × 2 FLOPs × T·d each, × 3 for training).

    ``causal=True`` (the default, matching every model in this repo)
    counts the attention term at T/2 — the work a causal kernel actually
    performs, since the flash kernels skip above-diagonal blocks
    entirely (compute AND DMA).  Set ``causal=False`` for the PaLM-style
    full-score-matrix convention; at long context the two differ by up
    to 2× on the attention term, so MFU tables must say which they use
    (docs/PERF.md reports the causal/performed convention)."""
    attn = 12.0 * n_layers * d_model * seq_len
    if causal:
        attn /= 2.0
    return 6.0 * n_params + attn


def mfu(
    achieved_flops_per_sec: float, peak_tflops: float = DEFAULT_PEAK_TFLOPS
) -> float:
    return achieved_flops_per_sec / (peak_tflops * 1e12)
