"""Shared runner behind the four entrypoints.

The reference's four parts are copy-pasted clones varying only in the
gradient-sync layer (SURVEY.md §1); here one runner takes the strategy
(and each part's constants) as parameters.  The reference CLI flags are
kept verbatim (north-star): ``--master-ip`` (default ``127.0.1.1:8000``),
``--rank`` (0), ``--num-nodes`` (1) — ``part2/2a/main.py:210-218``.
"""

from __future__ import annotations

import argparse
import contextlib
import os

import jax

from distributed_machine_learning_tpu.data.cifar10 import load_cifar10
from distributed_machine_learning_tpu.data.distributed_loader import (
    DistributedBatchLoader,
)
from distributed_machine_learning_tpu.data.loader import BatchLoader
from distributed_machine_learning_tpu.models.registry import get_model, list_models
from distributed_machine_learning_tpu.parallel.strategies import get_strategy
from distributed_machine_learning_tpu.runtime.distributed import (
    DEFAULT_MASTER_IP,
    initialize_from_flags,
)
from distributed_machine_learning_tpu.runtime.mesh import make_mesh
from distributed_machine_learning_tpu.train.loop import evaluate, train_epoch
from distributed_machine_learning_tpu.train.sgd import SGDConfig
from distributed_machine_learning_tpu.train.state import TrainState
from distributed_machine_learning_tpu.train.step import (
    make_eval_step,
    make_train_step,
    shard_batch,
)
from distributed_machine_learning_tpu.utils.logging import rank0_print
from distributed_machine_learning_tpu.utils.profiling import MetricsLogger, trace

SEED = 69143  # part1/main.py:17
EVAL_BATCH = 256


def add_node_flags(parser: argparse.ArgumentParser) -> None:
    """The reference's exact connectivity flags (part2/2a/main.py:210-218)
    — one definition shared by every entrypoint parser."""
    parser.add_argument("--master-ip", dest="master_ip", default=DEFAULT_MASTER_IP,
                        type=str, help="coordinator address host:port")
    parser.add_argument("--rank", default=0, type=int, help="process rank")
    parser.add_argument("--num-nodes", dest="num_nodes", default=1, type=int,
                        help="number of processes")


def add_telemetry_flags(parser: argparse.ArgumentParser) -> None:
    """The streaming-telemetry flags, shared by the CNN parts and the LM
    entrypoint (one definition, like ``add_node_flags``)."""
    parser.add_argument("--telemetry-dir", dest="telemetry_dir",
                        default=None, type=str,
                        help="stream run telemetry here: metrics.jsonl "
                             "(per-step rows, attempt-tagged, fsynced "
                             "every --telemetry-flush-every rows — "
                             "crash-safe, restarts append), trace.json "
                             "(Chrome trace of driver phases: data_wait/"
                             "place_batch/step_dispatch/device_block/"
                             "checkpoint_save/eval/restart_attempt; open "
                             "in ui.perfetto.dev), registry.json + "
                             "metrics.prom (final counters/quantiles). "
                             "Off by default: zero per-step cost")
    parser.add_argument("--telemetry-flush-every",
                        dest="telemetry_flush_every", default=20, type=int,
                        help="flush+fsync the telemetry sinks every N "
                             "rows/events (default 20); lower = smaller "
                             "crash-loss window, more write syscalls")


def add_gang_flags(parser: argparse.ArgumentParser) -> None:
    """Gang-coordination flags (``runtime/coordinator.py``): multi-host
    runs that share a filesystem get heartbeat-based peer-failure
    detection and coordinated abort, so one dead rank restarts the gang
    instead of hanging it forever."""
    parser.add_argument("--gang-dir", dest="gang_dir", default=None,
                        type=str,
                        help="shared directory for gang coordination "
                             "(heartbeat files, abort latch, restore-"
                             "point records — runtime/coordinator.py); "
                             "enables peer-failure detection: a rank "
                             "dead/stalled past --peer-timeout aborts "
                             "the whole gang (exit 43) so an external "
                             "gang supervisor (cli/gang.py, "
                             "gang_supervise) can relaunch all ranks "
                             "together from the agreed restore point. "
                             "Off by default")
    parser.add_argument("--heartbeat-interval", dest="heartbeat_interval",
                        default=1.0, type=float,
                        help="seconds between heartbeat-file writes "
                             "(with --gang-dir; default 1.0)")
    parser.add_argument("--peer-timeout", dest="peer_timeout",
                        default=60.0, type=float,
                        help="seconds without peer progress before this "
                             "rank declares the gang dead and aborts "
                             "(with --gang-dir; default 60; set it above "
                             "the first step's XLA compile time)")


def make_flag_parser(description: str) -> argparse.ArgumentParser:
    """The reference's exact flag surface (part2/2a/main.py:210-218)."""
    parser = argparse.ArgumentParser(description=description)
    add_node_flags(parser)
    add_gang_flags(parser)
    parser.add_argument("--data-root", default="./data", type=str)
    parser.add_argument("--epochs", default=1, type=int)  # range(1): part1/main.py:123
    parser.add_argument("--compute-dtype", default="float32",
                        choices=["float32", "bfloat16"],
                        help="trunk compute dtype (bfloat16 targets the MXU)")
    # Extensions beyond the reference surface (defaults reproduce it).
    parser.add_argument("--model", default="vgg11", type=str,
                        choices=list_models(),
                        help="model to train; default reproduces the "
                             "reference's VGG11")
    parser.add_argument("--max-iters", default=40, type=int,
                        help="training iteration cap (reference: 40)")
    parser.add_argument("--batch-size", default=None, type=int,
                        help="override the part's per-worker batch size")
    parser.add_argument("--eval-batches", default=None, type=int,
                        help="cap eval batches (default: full test set)")
    parser.add_argument("--eval-batch-size", dest="eval_batch_size",
                        default=EVAL_BATCH, type=int,
                        help="eval batch size (default 256; the compile "
                             "cost of the eval program scales with it on "
                             "CPU hosts, so short smoke runs want it small)")
    parser.add_argument("--ckpt-dir", default=None, type=str,
                        help="checkpoint directory; saves TrainState after "
                             "each epoch (off by default — reference parity)")
    parser.add_argument("--async-ckpt", dest="async_ckpt",
                        action="store_true",
                        help="write checkpoints asynchronously (orbax "
                             "background thread; train/checkpoint.py::"
                             "AsyncCheckpointWriter) — training continues "
                             "while the save serializes; the run waits for "
                             "the last save before exiting")
    parser.add_argument("--resume", nargs="?", const="latest", default=None,
                        choices=["latest", "auto"],
                        help="resume weights/optimizer/step from the latest "
                             "complete checkpoint in --ckpt-dir; the run then "
                             "trains --epochs further epochs (the epoch count "
                             "is not offset by prior progress).  '--resume "
                             "auto' additionally supervises the run: on a "
                             "stall, crash, or preemption it restores the "
                             "newest complete checkpoint and continues, up "
                             "to --max-restarts times (runtime/supervisor.py)")
    parser.add_argument("--max-restarts", dest="max_restarts", default=3,
                        type=int,
                        help="with --resume auto: restore-and-continue this "
                             "many times before giving up (default 3)")
    parser.add_argument("--keep-last-n", dest="keep_last_n", default=None,
                        type=int,
                        help="garbage-collect all but the newest N complete "
                             "checkpoints after each save (supervised long "
                             "runs checkpoint often; default keeps "
                             "everything).  The newest complete checkpoint "
                             "is never deleted")
    parser.add_argument("--guard-nonfinite", dest="guard_nonfinite",
                        action="store_true",
                        help="compile a non-finite-gradient guard into the "
                             "train step: a NaN/Inf gradient skips that "
                             "update (state unchanged, step not counted) "
                             "instead of poisoning the params; skips are "
                             "counted in the resilience summary")
    parser.add_argument("--loader-retries", dest="loader_retries", default=0,
                        type=int,
                        help="retry the training data iterator this many "
                             "times on exceptions (exponential backoff; a "
                             "batch failing twice is skipped — "
                             "data/retry.py); 0 disables")
    parser.add_argument("--faults", default=None, type=str,
                        help="deterministic fault injection spec, e.g. "
                             "'nan@2,raise@4,stall@7:2.5,kill_ckpt@1' "
                             "(runtime/faults.py; also read from the "
                             "DML_FAULTS env var); chaos-testing only, "
                             "off by default")
    parser.add_argument("--trace-dir", default=None, type=str,
                        help="write a jax.profiler trace of the training "
                             "loop here (view with TensorBoard/Perfetto)")
    parser.add_argument("--metrics-file", default=None, type=str,
                        help="write per-step metrics (step, loss, iteration "
                             "seconds) here; .csv for CSV, else JSONL "
                             "(JSONL streams to disk as rows land — a "
                             "crash keeps everything already flushed)")
    add_telemetry_flags(parser)
    parser.add_argument("--loader", default="auto",
                        choices=["auto", "python", "native"],
                        help="batch loader backend: 'native' is the C++ "
                             "prefetching worker (native/dataloader.cc), "
                             "'python' the pure-Python loader, 'auto' "
                             "native-if-buildable (identical batch streams "
                             "either way)")
    parser.add_argument("--lr-schedule", dest="lr_schedule", default="constant",
                        choices=["constant", "cosine", "step"],
                        help="learning-rate schedule (train/schedule.py); "
                             "'constant' reproduces the reference's fixed "
                             "lr=0.1, 'cosine' adds linear warmup + cosine "
                             "decay over the run, 'step' decays 10x at 50%% "
                             "and 75%% of the run")
    parser.add_argument("--warmup-steps", dest="warmup_steps", default=0,
                        type=int, help="warmup steps for --lr-schedule=cosine")
    parser.add_argument("--clip-norm", dest="clip_norm", default=None,
                        type=float,
                        help="clip the (synced) gradient to this global L2 "
                             "norm before the update (off by default). "
                             "Clips whatever the sync strategy produced: "
                             "part2a/2b SUM gradients over the world "
                             "(reference semantics, SURVEY.md §2.4), so "
                             "their clip engages world-size-times earlier "
                             "than part3's mean gradient — and once it "
                             "engages, a clipped SUM equals a clipped "
                             "mean, cancelling the SUM strategies' "
                             "effective-LR scaling")
    from distributed_machine_learning_tpu.train.optimizers import (
        optimizer_names,
    )

    parser.add_argument("--optimizer", default="sgd", choices=optimizer_names(),
                        help="'sgd' reproduces the reference "
                             "(lr=0.1/momentum/wd — part1/main.py:120-121); "
                             "'lars' adds layer-wise adaptive rate scaling "
                             "for large global batches (train/lars.py); "
                             "'adamw' is the decoupled-decay Adam "
                             "(train/adamw.py)")
    parser.add_argument("--fused-update", dest="fused_update",
                        action="store_true",
                        help="run the AdamW update as the fused one-pass "
                             "Pallas kernel (ops/pallas/fused_adamw.py): "
                             "moment update, bias correction, weight "
                             "decay, parameter update and the dtype cast "
                             "in-register per tile — the round-13 "
                             "update-phase lever; --optimizer adamw only "
                             "(documented-ulp parity with the reference "
                             "update)")
    parser.add_argument("--wire-dtype", dest="wire_dtype", default=None,
                        choices=["bfloat16"],
                        help="DEPRECATED: use --ring-compress bf16 (this "
                             "is the cast-only wire compression, kept for "
                             "compatibility)")
    parser.add_argument("--ring-compress", dest="ring_compress",
                        default="none",
                        choices=["none", "bf16", "int8", "topk"],
                        help="ring all-reduce wire compression (part3 "
                             "ring only; ops/ring.py): 'bf16' casts each "
                             "hop's payload (2x fewer bytes, no residual "
                             "correction), 'int8' is per-chunk symmetric "
                             "int8 + fp32 scale fused into each hop (~4x "
                             "fewer bytes), 'topk' sends only the "
                             "largest --ring-topk-frac of each chunk "
                             "(values+indices).  int8/topk carry an "
                             "error-feedback residual across steps "
                             "(EF-SGD) unless --ring-no-error-feedback")
    parser.add_argument("--ring-codec-impl", dest="ring_codec_impl",
                        default="xla", choices=["xla", "pallas"],
                        help="implementation of the int8 ring codec "
                             "(round 13): 'pallas' runs each hop's "
                             "dequantize-add-requantize and the EF "
                             "residual as fused in-register kernels "
                             "(ops/pallas/ring_codec.py) — bitwise-"
                             "identical to 'xla', no dequantized "
                             "partial in HBM; only --ring-compress "
                             "int8 has kernels (bf16/topk keep the "
                             "XLA path)")
    parser.add_argument("--ring-topk-frac", dest="ring_topk_frac",
                        default=0.125, type=float,
                        help="fraction of each ring chunk kept by "
                             "--ring-compress topk (default 0.125 = 4x "
                             "fewer wire bytes at fp32 values + int32 "
                             "indices)")
    parser.add_argument("--ring-no-error-feedback",
                        dest="ring_error_feedback", action="store_false",
                        help="disable the error-feedback residual for "
                             "--ring-compress int8/topk (ablation only: "
                             "the dropped compression error is then lost "
                             "instead of re-injected next step)")
    parser.add_argument("--ring-topology", dest="ring_topology",
                        default=None, metavar="INNERxOUTER",
                        help="topology-aware hierarchical ring (part3 "
                             "ring only; ops/topology.py): factor the "
                             "data axis as INNERxOUTER (e.g. 2x4 = "
                             "2-chip nodes × 4 nodes; the product must "
                             "equal the world size) and all-reduce as "
                             "reduce-scatter on the fast inner axis, a "
                             "--ring-compress'd ring on the slow outer "
                             "axis over 1/INNER of the data (inter-node "
                             "traffic drops ~INNER-fold), all-gather "
                             "back down; small buckets take a recursive "
                             "halving-doubling latency path.  A 1-sized "
                             "axis degenerates to the flat ring")
    parser.add_argument("--dist-eval", dest="dist_eval", action="store_true",
                        help="shard evaluation batches over the mesh "
                             "(pmean/psum reductions) instead of the "
                             "reference's every-rank-evaluates-everything "
                             "protocol; identical results, N-fold faster")
    parser.add_argument("--watchdog-timeout", dest="watchdog_timeout",
                        default=0, type=float,
                        help="seconds without a completed step before the "
                             "watchdog (runtime/resilience.py) declares a "
                             "stall and dumps thread stacks — detects hung "
                             "collectives (a dead peer leaves the reference "
                             "blocked forever, SURVEY.md §5); 0 disables. "
                             "Set it above the first step's XLA compile "
                             "time (~20-40s cold)")
    parser.add_argument("--local-loss", dest="local_loss", action="store_true",
                        help="print each device's own shard loss instead of "
                             "the global mean — the reference's per-rank "
                             "print surface (part2/2a/main.py:58-61); "
                             "distributed parts only")
    parser.add_argument("--unsync-bn", dest="unsync_bn", action="store_true",
                        help="per-device BatchNorm running stats (the "
                             "reference part3's documented quirk: per-node "
                             "stats, <1%% cross-node accuracy drift — "
                             "part3/model.py:24, group25.pdf p.3-4); "
                             "default axis-syncs the stats")
    parser.add_argument("--grad-accum", dest="grad_accum", default=1, type=int,
                        help="split each per-device batch into this many "
                             "sequential microbatches, accumulating "
                             "gradients for one update (accum-fold lower "
                             "activation memory; identical update when "
                             "augmentation is off — with augmentation each "
                             "microbatch draws its own crops/flips, and BN "
                             "stats update per microbatch)")
    return parser


def make_schedule(args, learning_rate: float, start_step: int = 0):
    """Build the ``step -> lr`` schedule the flags describe (None for the
    reference's fixed rate).

    ``start_step``: the state's step counter at run start (non-zero after
    ``--resume``).  The horizon covers *this run's* ``max_iters × epochs``
    from there — otherwise a resumed cosine run would start past its own
    total_steps and train at end_lr (zero) throughout.
    """
    from distributed_machine_learning_tpu.train.schedule import (
        step_decay,
        warmup_cosine,
    )

    total = max(args.max_iters * args.epochs, 1)
    if args.lr_schedule == "cosine":
        # parse_flags guarantees 0 <= warmup_steps < total.
        base = warmup_cosine(learning_rate, args.warmup_steps, total)
    elif args.lr_schedule == "step":
        base = step_decay(
            learning_rate, boundaries=(total // 2, (3 * total) // 4)
        )
    else:
        return None
    if start_step:
        return lambda step: base(step - start_step)
    return base


def parse_flags(parser: argparse.ArgumentParser, argv=None) -> argparse.Namespace:
    """parse_args + cross-flag validation (fail at parse time, before any
    distributed runtime spin-up)."""
    args = parser.parse_args(argv)
    if args.resume and not args.ckpt_dir:
        parser.error("--resume requires --ckpt-dir")
    if args.max_restarts < 0:
        parser.error(f"--max-restarts must be >= 0, got {args.max_restarts}")
    if args.keep_last_n is not None and args.keep_last_n < 1:
        parser.error(f"--keep-last-n must be >= 1, got {args.keep_last_n}")
    if args.loader_retries < 0:
        parser.error(
            f"--loader-retries must be >= 0, got {args.loader_retries}"
        )
    if args.faults:
        from distributed_machine_learning_tpu.runtime.faults import (
            FaultInjector,
        )

        try:  # validate the spec at parse time, before any runtime spin-up
            FaultInjector.parse(args.faults)
        except ValueError as e:
            parser.error(f"--faults: {e}")
    if args.clip_norm is not None and args.clip_norm <= 0:
        parser.error(f"--clip-norm must be positive, got {args.clip_norm}")
    frac = getattr(args, "ring_topk_frac", 0.125)
    if not 0.0 < frac <= 1.0:
        parser.error(f"--ring-topk-frac must be in (0, 1], got {frac}")
    if getattr(args, "ring_topology", None):
        from distributed_machine_learning_tpu.ops.topology import (
            parse_topology,
        )

        try:  # malformed/zero-axis specs die at parse time; the
            # world-equality half runs once the mesh exists (run_part)
            parse_topology(args.ring_topology)
        except ValueError as e:
            parser.error(f"--ring-topology: {e}")
    if args.grad_accum < 1:
        parser.error(f"--grad-accum must be >= 1, got {args.grad_accum}")
    if args.warmup_steps < 0:
        parser.error(f"--warmup-steps must be >= 0, got {args.warmup_steps}")
    if getattr(args, "telemetry_flush_every", 20) < 1:
        parser.error(
            f"--telemetry-flush-every must be >= 1, got "
            f"{args.telemetry_flush_every}"
        )
    if getattr(args, "gang_dir", None):
        hb = getattr(args, "heartbeat_interval", 1.0)
        if hb <= 0:
            parser.error(
                f"--heartbeat-interval must be > 0, got {hb}"
            )
        if getattr(args, "peer_timeout", 60.0) <= 2 * hb:
            parser.error(
                "--peer-timeout must exceed two heartbeat intervals "
                "(a single delayed write would read as a death)"
            )
        if getattr(args, "async_ckpt", False):
            # Restore-point records are written when a save RETURNS
            # complete; the async writer commits later, so no rank
            # would ever record a step and the election would silently
            # never elect — ranks could then resume from different
            # steps after a gang restart.
            parser.error(
                "--gang-dir requires the synchronous checkpoint path "
                "(drop --async-ckpt): the restore-point election needs "
                "saves recorded at commit time"
            )
    if args.lr_schedule == "cosine":
        total = args.max_iters * args.epochs
        if args.warmup_steps >= total:
            parser.error(
                f"--warmup-steps {args.warmup_steps} must be shorter than "
                f"the run (max_iters × epochs = {total} steps): the rate "
                "would never reach its peak"
            )
    return args


def init_model_and_state(model, seed: int = SEED, config: SGDConfig | None = None):
    """Initialize once from the shared seed → identical weights everywhere,
    the property the reference gets by seeding every rank before building
    the model (``part2/2a/main.py:199``, SURVEY.md §2.5)."""
    rng = jax.random.PRNGKey(seed)
    init_rng, state_rng = jax.random.split(rng)
    variables = model.init(init_rng, jax.numpy.zeros((1, 32, 32, 3)), train=False)
    return TrainState.create(
        params=variables["params"],
        batch_stats=variables.get("batch_stats"),
        rng=state_rng,
        config=config,
    )


def run_part(
    strategy_name: str,
    per_rank_batch: int,
    use_bn: bool,
    args,
    strategy_kwargs: dict | None = None,
) -> None:
    """Train `args.model` (default VGG-11) on CIFAR-10 for `args.epochs`
    under one sync strategy."""
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.runtime.faults import FaultEvents

    # Streaming mode: rows hit the disk as they land (rank-0 gated,
    # periodic fsync) instead of only at exit — a crash keeps history.
    # Append only when this run CONTINUES prior work (--resume): a
    # restart then extends the survivor rows.  A fresh run truncates,
    # the historical semantics — appending would silently mix
    # unrelated runs in one file.
    metrics = (
        MetricsLogger(path=args.metrics_file,
                      flush_every=getattr(args, "telemetry_flush_every", 20),
                      append=bool(args.resume))
        if args.metrics_file else None
    )
    from distributed_machine_learning_tpu.telemetry import (
        set_telemetry,
        telemetry_from_flags,
    )

    telemetry = telemetry_from_flags(args)
    prev_telemetry = None
    if telemetry is not None:
        # Installed process-wide so the deep layers (loader queue gauge,
        # retry counters, checkpoint spans, FaultEvents mirror,
        # supervisor restart spans) see it without signature threading.
        prev_telemetry = set_telemetry(telemetry)
        from distributed_machine_learning_tpu.models.vgg import _cfg
        from distributed_machine_learning_tpu.utils.flops import (
            vgg_train_flops_per_image,
        )

        if args.model.upper() in _cfg:
            # MFU cost model (utils/flops.py); non-VGG models log
            # throughput without MFU rather than against a wrong model.
            telemetry.flops_per_example = vgg_train_flops_per_image(
                _cfg[args.model.upper()]
            )
    ctx = initialize_from_flags(args.master_ip, args.rank, args.num_nodes)
    preemption = None
    watchdog = None
    ckpt_writer = None
    coordinator = None
    run_completed = False
    events = FaultEvents()
    show_resilience = False
    try:
        distributed = strategy_name != "none"
        mesh = make_mesh() if distributed else None
        world = mesh.shape["batch"] if mesh is not None else 1
        # Reference banner (part2/2a/main.py:200-203).
        rank0_print(
            f"strategy={strategy_name} world_size={world} "
            f"devices={jax.device_count()} processes={jax.process_count()}"
        )

        compute_dtype = jnp.bfloat16 if args.compute_dtype == "bfloat16" else jnp.float32
        model = get_model(args.model, use_bn=use_bn,
                          compute_dtype=compute_dtype)
        from distributed_machine_learning_tpu.train.optimizers import (
            get_optimizer,
        )

        opt_config = get_optimizer(args.optimizer)[0]()
        if getattr(args, "fused_update", False):
            from distributed_machine_learning_tpu.train.adamw import (
                AdamWConfig,
            )

            if isinstance(opt_config, AdamWConfig):
                import dataclasses

                opt_config = dataclasses.replace(opt_config, fused=True)
            else:
                rank0_print(
                    "WARNING: --fused-update applies to --optimizer adamw "
                    f"only; {args.optimizer!r} runs its reference update."
                )
        state = init_model_and_state(model, config=opt_config)

        # Unsynced-BN quirk mode (reference part3 parity: per-node running
        # stats — part3/model.py:24, group25.pdf p.3-4).  Decided BEFORE
        # --resume so the checkpoint-restore template carries the stacked
        # [world, C] stats layout a quirk-mode checkpoint was saved with.
        unsync_bn = bool(getattr(args, "unsync_bn", False))
        if unsync_bn and mesh is None:
            rank0_print("WARNING: --unsync-bn has no effect on the "
                        "single-device part1 path (one device, one set of "
                        "stats).")
            unsync_bn = False
        if unsync_bn and not state.batch_stats:
            unsync_bn = False  # BN-free model: nothing to (un)sync
        from distributed_machine_learning_tpu.train.step import (
            broadcast_bn_stats,
        )

        def _maybe_stack(st):
            return broadcast_bn_stats(st, world) if unsync_bn else st

        state = _maybe_stack(state)

        def restore_latest(fresh_state):
            """State from the newest complete checkpoint in --ckpt-dir
            (or ``fresh_state`` when none exists).  Factored so the
            supervised mode (--resume auto) can re-run it after every
            restart — the auto-resume leg of the skip/retry/restart
            ladder."""
            state = fresh_state
            from distributed_machine_learning_tpu.train.checkpoint import (
                NoRestorableCheckpointError,
                checkpoint_chain_report,
                checkpoint_config,
                latest_checkpoint,
                restore_checkpoint,
            )

            if not args.ckpt_dir:
                raise ValueError("--resume requires --ckpt-dir")
            latest = latest_checkpoint(args.ckpt_dir, events=events)
            if latest is None:
                report = checkpoint_chain_report(args.ckpt_dir)
                if any(v.startswith("quarantined") for _, v in report):
                    # Real checkpoints existed and every one was
                    # CONDEMNED (quarantined — bad digests, or a gang
                    # election verdict): silently training from scratch
                    # over a dir full of condemned checkpoints is how
                    # runs lose weeks — fail loudly with the
                    # per-candidate verdicts.  Incomplete-only leftovers
                    # (a crash during the first save) still start from
                    # scratch silently: that IS the resume guarantee.
                    lines = "\n".join(f"  {p}: {v}" for p, v in report)
                    raise NoRestorableCheckpointError(
                        f"--resume: no restorable checkpoint under "
                        f"{args.ckpt_dir} — every candidate in the "
                        f"fallback chain is unusable:\n{lines}\n"
                        "(remove --resume, or point --ckpt-dir at a "
                        "clean directory, to start from scratch)"
                    )
                rank0_print(f"No checkpoint under {args.ckpt_dir}; "
                            "starting from scratch.")
            else:
                # The restore template must use the *saved* momentum
                # layout (AdamW's {"mu","nu"} dict vs SGD's buffer tree);
                # a cross-optimizer resume rebuilds it below.
                saved_cfg = checkpoint_config(latest)
                abstract = (
                    state
                    if type(saved_cfg) is type(opt_config)
                    else _maybe_stack(
                        init_model_and_state(model, config=saved_cfg)
                    )
                )
                # In quirk mode, pick the restore template by the SAVED
                # stats layout — a metadata read (no array IO) — rather
                # than retrying on a blanket except, which would also
                # mask unrelated restore failures (corrupt checkpoint,
                # dtype/optimizer mismatch) behind a second confusing
                # error.
                restore_against = abstract
                stack_after = False
                if unsync_bn:
                    from distributed_machine_learning_tpu.train.checkpoint import (  # noqa: E501
                        checkpoint_array_shapes,
                    )

                    saved_stats = checkpoint_array_shapes(latest).get(
                        "batch_stats"
                    ) or {}
                    saved_leaves = jax.tree_util.tree_leaves(
                        saved_stats, is_leaf=lambda x: isinstance(x, tuple)
                    )
                    want_leaves = jax.tree_util.tree_leaves(
                        abstract.batch_stats
                    )
                    if (saved_leaves and want_leaves
                            and len(saved_leaves[0])
                            < want_leaves[0].ndim):
                        # The checkpoint predates --unsync-bn (plain [C]
                        # stats): restore against the plain template,
                        # then enter quirk mode by stacking the restored
                        # stats.
                        restore_against = init_model_and_state(
                            model,
                            config=saved_cfg
                            if type(saved_cfg) is not type(opt_config)
                            else opt_config,
                        )
                        stack_after = True
                state = restore_checkpoint(
                    latest, abstract_state=restore_against,
                    files_verified=True,  # latest_checkpoint just swept
                )
                if stack_after:
                    state = _maybe_stack(state)
                rank0_print(f"Resumed from {latest} (step "
                            f"{int(jax.device_get(state.step))})")
                want = opt_config
                if type(state.config) is not type(want):
                    # The checkpoint records its optimizer config class;
                    # SGD's (raw-gradient-scale) and LARS's
                    # (lr·trust·ratio-scaled) momentum buffers are not
                    # interchangeable, so switching optimizers at resume
                    # resets them rather than misapplying them.
                    rank0_print(
                        f"WARNING: checkpoint was trained with "
                        f"{type(state.config).__name__} but this run uses "
                        f"--optimizer {args.optimizer}; resetting momentum "
                        "buffers (params/step/stats are kept)."
                    )
                    from distributed_machine_learning_tpu.train.optimizers import (
                        init_for_config,
                    )

                    state = state.replace(
                        config=want,
                        # Fresh buffers in the NEW optimizer's layout —
                        # zeroing the old tree would hand e.g. an SGD
                        # buffer tree to AdamW's {"mu","nu"} update.
                        momentum=init_for_config(want)(state.params),
                    )
                if mesh is not None:
                    # Restored arrays come back committed to the default
                    # device; the distributed step needs them replicated
                    # over the mesh (the shard_map's in_specs say P()) —
                    # mixing a device-0-committed state with mesh-sharded
                    # batches is a hard error, not just slow.
                    from jax.sharding import NamedSharding, PartitionSpec

                    state = jax.device_put(
                        state, NamedSharding(mesh, PartitionSpec())
                    )
            return state

        if args.resume:
            state = restore_latest(state)
        strategy_kwargs = dict(strategy_kwargs or {})
        ring_compress = getattr(args, "ring_compress", "none")
        if args.wire_dtype:
            # --wire-dtype is subsumed by --ring-compress bf16 (same
            # cast-only wire path); keep it working, steer users over.
            rank0_print(
                "WARNING: --wire-dtype is deprecated; use --ring-compress "
                "bf16 (cast-only) or --ring-compress int8/topk for the "
                "error-feedback compressed ring."
            )
            if ring_compress == "none":
                ring_compress = "bf16"
        ring_topology = getattr(args, "ring_topology", None)
        ring_codec_impl = getattr(args, "ring_codec_impl", "xla")
        if strategy_name == "ring":
            if ring_compress != "none":
                strategy_kwargs["compress"] = ring_compress
                strategy_kwargs["topk_frac"] = getattr(
                    args, "ring_topk_frac", 0.125
                )
                strategy_kwargs["error_feedback"] = getattr(
                    args, "ring_error_feedback", True
                )
            if ring_codec_impl != "xla":
                if ring_compress != "int8":
                    rank0_print(
                        "WARNING: --ring-codec-impl pallas has kernels for "
                        "--ring-compress int8 only; "
                        f"{ring_compress!r} runs the XLA path."
                    )
                strategy_kwargs["codec_impl"] = ring_codec_impl
            if ring_topology:
                strategy_kwargs["topology"] = ring_topology
        elif ring_compress != "none":
            rank0_print(
                "WARNING: --ring-compress/--wire-dtype only apply to the "
                f"ring strategy (part3); strategy {strategy_name!r} runs "
                "uncompressed."
            )
        if strategy_name != "ring" and ring_topology:
            rank0_print(
                "WARNING: --ring-topology only applies to the ring "
                f"strategy (part3); strategy {strategy_name!r} runs the "
                "flat collective."
            )
        # Reference part1 prints a torchsummary table before training
        # (part1/main.py:118; the ~9.2M-param total the report leans on).
        from distributed_machine_learning_tpu.utils.summary import model_summary

        rank0_print(model_summary(state.params, title=args.model))

        strategy = get_strategy(strategy_name, **strategy_kwargs)
        if hasattr(strategy, "topology_for"):
            # Fail the factorization mismatch HERE — before any data
            # loading or compilation — with the flag-level message
            # (inner×outer must equal the mesh world; topology_for is
            # also what the train step resolves per call, so a passing
            # check here is the same check the program will use).
            strategy.topology_for(world)
        if args.resume and getattr(strategy, "stateful", False):
            # The EF residual is per-device step-wrapper state, not part
            # of TrainState: a resumed run starts it at zero (one step
            # of EF warmup), so its trajectory can differ slightly from
            # an uninterrupted run's — say so rather than silently
            # weakening the resume-exactness story.
            rank0_print(
                "NOTE: error-feedback residuals (--ring-compress "
                f"{strategy.compress}) are not checkpointed; resuming "
                "with a zero residual (one step of EF warmup)."
            )
        if (telemetry is not None and mesh is not None
                and hasattr(strategy, "wire_bytes_per_step")):
            # Static per-step wire accounting: the ring's bytes-on-the-
            # wire are a compile-time property of (param count, world,
            # bucket size, codec), so the counter increment is computed
            # once here and applied per step by the train loop —
            # gang benches and tools/trace_summary.py read the totals
            # back out of registry.json.
            n_elems = sum(
                int(l.size) for l in jax.tree_util.tree_leaves(state.params)
            )
            # Split by mesh axis (round 11): the flat ring counts under
            # {axis="flat"}; a --ring-topology run counts inner
            # (intra-node) and outer (inter-node) bytes separately so
            # tools/trace_summary.py can show the bottleneck-link
            # reduction, not just the total.
            telemetry.step_counters["ring_wire_bytes"] = [
                ({"axis": ax}, b)
                for ax, b in strategy.wire_bytes_by_axis(
                    n_elems, world
                ).items()
                if b
            ]
            telemetry.registry.gauge("ring_compression_ratio").set(
                strategy.compression_ratio(n_elems, world)
            )
        if telemetry is not None:
            # Which implementation actually ran, visible per step in the
            # registry/trace (round 13): a bench or gang row claiming
            # "fused" must show a nonzero counter, and a silent fallback
            # to the XLA path shows as its absence.
            if (getattr(strategy, "codec_impl", "xla") == "pallas"
                    and getattr(strategy, "compress", "none") == "int8"):
                telemetry.step_counters["fused_codec_steps"] = 1
            if getattr(opt_config, "fused", False):
                telemetry.step_counters["fused_update_steps"] = 1
        train_step = make_train_step(
            model, strategy, mesh=mesh,
            schedule=make_schedule(
                args, state.config.learning_rate,
                start_step=int(jax.device_get(state.step)),
            ),
            clip_norm=args.clip_norm,
            accum_steps=args.grad_accum,
            optimizer=args.optimizer,
            sync_bn=not unsync_bn,
            local_loss=bool(getattr(args, "local_loss", False))
            and mesh is not None,
            guard_nonfinite=bool(getattr(args, "guard_nonfinite", False)),
        )
        eval_step = make_eval_step(model)
        if unsync_bn and state.batch_stats:
            # Quirk-mode stats are [world, *S]-stacked; the single-device
            # eval step can't consume them — evaluate with device 0's row
            # (each reference node evaluates with its own stats; rank 0's
            # is the one whose prints we surface).
            base_eval = eval_step

            def eval_step(params, stats, images, labels):
                stats0 = jax.tree_util.tree_map(lambda s: s[0], stats)
                return base_eval(params, stats0, images, labels)
        if args.dist_eval and mesh is None:
            rank0_print(
                "WARNING: --dist-eval has no effect for the single-device "
                "part1 path (no mesh to shard over); evaluating on one "
                "device."
            )
        if args.dist_eval and mesh is not None:
            # Sharded eval for world-size-divisible batches; the single
            # device step covers the test set's short final batch (the
            # reference instead evaluates everything on every rank —
            # SURVEY.md §3.5).
            # sync_bn=False makes the sharded eval read each device's own
            # row of quirk-mode stacked stats (make_eval_step docstring).
            dist_eval, single_eval = (
                make_eval_step(model, mesh=mesh, sync_bn=not unsync_bn),
                eval_step,
            )

            def eval_step(params, stats, images, labels):
                fn = dist_eval if len(labels) % world == 0 else single_eval
                return fn(params, stats, images, labels)

        train_set = load_cifar10(args.data_root, train=True)
        test_set = load_cifar10(args.data_root, train=False)
        if train_set.synthetic:
            rank0_print("WARNING: CIFAR-10 not found on disk — using the "
                        "deterministic synthetic stand-in dataset.")

        if args.batch_size is not None:
            per_rank_batch = args.batch_size

        loader_cls, dist_loader_cls = BatchLoader, DistributedBatchLoader
        loader_choice = getattr(args, "loader", "auto")
        if loader_choice in ("auto", "native"):
            from distributed_machine_learning_tpu.data.native_loader import (
                NativeBatchLoader,
                NativeDistributedBatchLoader,
                native_available,
                native_unavailable_reason,
            )

            if native_available():
                loader_cls, dist_loader_cls = (
                    NativeBatchLoader,
                    NativeDistributedBatchLoader,
                )
            elif loader_choice == "native":
                raise RuntimeError(native_unavailable_reason())
            else:
                rank0_print(
                    f"native loader unavailable, using python loader "
                    f"({native_unavailable_reason()})"
                )

        place = (lambda i, l: shard_batch(mesh, i, l)) if mesh is not None else None
        from distributed_machine_learning_tpu.runtime.faults import (
            FaultInjector,
        )
        from distributed_machine_learning_tpu.runtime.resilience import (
            PreemptionHandler,
            Watchdog,
            agree_stop,
            periodic_agree_stop,
        )

        supervised = args.resume == "auto"
        injector = FaultInjector.from_flags(
            getattr(args, "faults", None), seed=SEED,
            horizon=max(args.max_iters, 2),
        )
        if injector is not None and getattr(args, "gang_dir", None):
            # Gang mode: the exactly-once latch must survive the
            # coordinated relaunch a fault causes — without the ledger
            # every relaunched process re-parses the spec and re-fires
            # the same fault until the restart budget is gone.
            from distributed_machine_learning_tpu.runtime.faults import (
                FAULT_LEDGER_FILE,
            )

            os.makedirs(args.gang_dir, exist_ok=True)
            injector.attach_ledger(
                os.path.join(args.gang_dir, FAULT_LEDGER_FILE)
            )
        mid_save = (
            injector.mid_save_hook(events) if injector is not None else None
        )
        post_save = (
            injector.post_save_hook(events) if injector is not None else None
        )
        if (injector is not None and args.async_ckpt
                and (injector.has_kind("kill_ckpt")
                     or injector.has_kind("corrupt_ckpt"))):
            # The async writer defers the config file past the orbax
            # commit, so there is no synchronous "between state and
            # config" window to kill in, and it takes no post-save hook
            # to corrupt through — either fault would silently never
            # fire, which is worse than refusing.
            raise ValueError(
                "kill_ckpt/corrupt_ckpt faults require the synchronous "
                "checkpoint path (drop --async-ckpt)"
            )
        retry_policy = None
        if getattr(args, "loader_retries", 0):
            from distributed_machine_learning_tpu.data.retry import (
                RetryPolicy,
            )

            retry_policy = RetryPolicy(max_retries=args.loader_retries)
        show_resilience = (
            supervised or injector is not None
            or bool(getattr(args, "guard_nonfinite", False))
            or bool(getattr(args, "loader_retries", 0))
        )
        # Per-step fault accounting costs a host sync per step; only pay
        # it when some robustness feature can actually produce events.
        loop_events = events if show_resilience else None

        preemption = PreemptionHandler().install()
        # Multi-host: every host must leave the step loop at the SAME
        # boundary or the stragglers hang in a collective.  The in-loop
        # predicate agrees cross-host every few steps (per-step agreement
        # would tax every step with an allgather); the epoch tail agrees
        # unconditionally.
        in_loop_stop = periodic_agree_stop(lambda: preemption.requested)
        if getattr(args, "gang_dir", None):
            # Gang mode: heartbeat + peer-failure detection around the
            # whole run (runtime/coordinator.py).  A dead/stalled peer
            # aborts this process (exit 43) so an external gang
            # supervisor relaunches every rank together — the agreement
            # the in-process ladder above cannot provide once a rank is
            # stuck inside a collective.
            from distributed_machine_learning_tpu.runtime.coordinator import (  # noqa: E501
                GangCoordinator,
            )

            coordinator = GangCoordinator(
                args.gang_dir,
                rank=jax.process_index(),
                world=jax.process_count(),
                heartbeat_interval_s=args.heartbeat_interval,
                peer_timeout_s=args.peer_timeout,
                events=events,
            ).start()
            show_resilience = True
            if args.resume:
                # A successful restore is this rank's proof that the
                # restored checkpoint is whole — its half of the
                # restore-point election, recorded even if no further
                # save ever lands (gang_worker.py does the same).
                coordinator.record_valid_step(
                    int(jax.device_get(state.step))
                )
            base_in_loop_stop = in_loop_stop
            # Warm-up suspension: the first step's XLA compile can
            # outlast any sane peer timeout, and the stop predicate is
            # polled BEFORE each step — so stay suspended (liveness
            # still monitored, progress not judged) until the second
            # poll, which can only happen after the first step (and its
            # compile) completed.
            warmup_cm = coordinator.suspend()
            warmup_cm.__enter__()
            warmup = {"polls": 0, "cm": warmup_cm, "last": None,
                      "suspends": coordinator.suspensions}

            def in_loop_stop(_base=base_in_loop_stop):
                import time as _time

                # The stop predicate is polled once per step on every
                # rank — the natural place to record gang progress
                # without threading the coordinator into the loop.  The
                # inter-poll delta is one completed step, so past
                # warm-up each poll also feeds the heartbeat metric
                # snapshot (rolling step time) the gang straggler
                # detector compares across ranks.  A delta only counts
                # when NO suspension happened inside it: compile, eval
                # and checkpoint saves all run under coordinator
                # .suspend(), and an interval that swallowed one is not
                # a step time — feeding it would poison the rolling
                # mean for a whole window and fire false straggler
                # verdicts (`suspensions` is the entry counter the
                # coordinator keeps for exactly this comparison).
                now = _time.perf_counter()
                spans = coordinator.suspensions
                if (warmup["cm"] is None and warmup["last"] is not None
                        and spans == warmup["suspends"]):
                    coordinator.observe_step(warmup["polls"],
                                             now - warmup["last"])
                else:
                    coordinator.beat()
                warmup["last"] = now
                warmup["suspends"] = spans
                warmup["polls"] += 1
                if warmup["cm"] is not None and warmup["polls"] >= 2:
                    warmup["cm"].__exit__(None, None, None)
                    warmup["cm"] = None
                return _base()
        if args.watchdog_timeout and not supervised:
            watchdog = Watchdog(timeout_s=args.watchdog_timeout).start()
        # Epochs completed across supervised restarts: a restart resumes
        # from the per-epoch checkpoint, so finished epochs stay done.
        progress = {"epochs": 0}

        def make_epoch_batches():
            import itertools

            if distributed:
                base = dist_loader_cls(train_set, per_rank_batch, world)
            else:
                base = loader_cls(train_set, per_rank_batch)
            # Fault steps index the run's global batch ordinal; epochs
            # are --max-iters batches under the reference protocol.
            epoch_base = progress["epochs"] * args.max_iters

            def source(pos):
                # Seekable by re-slicing: every loader here is
                # deterministic, so skipping `pos - epoch_base` batches
                # replays the exact stream (data/retry.py's contract).
                it = itertools.islice(iter(base), pos - epoch_base, None)
                if injector is not None:
                    it = injector.wrap_batches(it, events, start=pos)
                return it

            if retry_policy is not None:
                from distributed_machine_learning_tpu.data.retry import (
                    retry_batches,
                )

                return retry_batches(
                    source, retry_policy, events, start=epoch_base
                )
            return source(epoch_base)

        def run_epochs(state, wd):
            """The per-epoch train/eval/checkpoint cycle; returns
            (state, stopped_early)."""
            nonlocal ckpt_writer
            while progress["epochs"] < args.epochs:
                batches = make_epoch_batches()
                if wd is not None:
                    # Reset the timer at the epoch boundary so the first
                    # step's XLA compile gets the full timeout window
                    # instead of whatever is left from the setup phase.
                    wd.beat()
                with trace(args.trace_dir):
                    state, _ = train_epoch(
                        train_step, state, batches, place_batch=place,
                        max_iters=args.max_iters, metrics=metrics,
                        stop=in_loop_stop, watchdog=wd,
                        events=loop_events, telemetry=telemetry,
                    )
                # One agreed decision governs the whole epoch tail —
                # eval, checkpoint, and loop exit must diverge on NO host.
                stopping = agree_stop(preemption.requested)
                if not stopping:
                    eval_batches = BatchLoader(
                        test_set, getattr(args, "eval_batch_size", EVAL_BATCH)
                    )
                    if args.eval_batches is not None:
                        import itertools

                        eval_batches = itertools.islice(
                            iter(eval_batches), args.eval_batches
                        )
                    # Eval time is not step time: suspend the stall
                    # clock so a long eval (including its own compile)
                    # can't be declared a stall — under --resume auto a
                    # declared stall costs a restart.
                    with (wd.suspend() if wd is not None
                          else contextlib.nullcontext()), \
                         (coordinator.suspend() if coordinator is not None
                          else contextlib.nullcontext()), \
                         (telemetry.span("eval", epoch=progress["epochs"])
                          if telemetry is not None
                          else contextlib.nullcontext()):
                        evaluate(eval_step, state, eval_batches)
                if args.ckpt_dir:
                    from distributed_machine_learning_tpu.train.checkpoint import (  # noqa: E501
                        AsyncCheckpointWriter,
                        save_checkpoint,
                    )

                    # Same for the (possibly long, blocking) checkpoint
                    # write: not step time — stop the stall clock.
                    with (wd.suspend() if wd is not None
                          else contextlib.nullcontext()), \
                         (coordinator.suspend() if coordinator is not None
                          else contextlib.nullcontext()):
                        if args.async_ckpt:
                            if ckpt_writer is None:
                                ckpt_writer = AsyncCheckpointWriter()
                            path = ckpt_writer.save(
                                args.ckpt_dir, state,
                                keep_last_n=getattr(args, "keep_last_n",
                                                    None),
                            )
                            rank0_print(
                                f"Saving checkpoint to {path} (async)"
                            )
                        else:
                            path = save_checkpoint(
                                args.ckpt_dir, state, mid_save_hook=mid_save,
                                keep_last_n=getattr(args, "keep_last_n",
                                                    None),
                                post_save_hook=post_save,
                            )
                            rank0_print(f"Saved checkpoint to {path}")
                            if coordinator is not None:
                                # This rank's half of the restore-point
                                # election: the save returned, so the
                                # checkpoint is locally verified.  (Async
                                # saves commit later; they are recorded
                                # only after the writer's flush, which
                                # the gang path doesn't use yet.)
                                coordinator.record_valid_step(
                                    int(jax.device_get(state.step))
                                )
                if stopping:
                    events.preemptions += 1
                    rank0_print(
                        "preemption checkpoint complete; exiting cleanly "
                        "(resume with --resume)"
                        if args.ckpt_dir
                        else "stop requested; exiting (no --ckpt-dir, so no "
                             "checkpoint was written)"
                    )
                    return state, True
                progress["epochs"] += 1
            return state, False

        if supervised:
            # --resume auto: the supervised ladder — on a stall, crash,
            # or injected death, restore the newest complete checkpoint
            # and continue where the per-epoch progress left off, up to
            # --max-restarts times (runtime/supervisor.py).
            from distributed_machine_learning_tpu.runtime.supervisor import (
                RaisingWatchdog,
                run_attempts,
            )

            def attempt(restart_idx):
                s = state
                if restart_idx > 0:
                    if ckpt_writer is not None:
                        # Flush the async writer's pending config before
                        # looking for the newest complete checkpoint:
                        # without this, the last scheduled save is still
                        # invisible to latest_checkpoint and the restart
                        # would silently drop an epoch of finished work.
                        try:
                            ckpt_writer.wait()
                        except Exception as e:
                            # Torn save stays incomplete; restore falls
                            # back to the previous complete one — but
                            # say so (dmlcheck DML005): a silently
                            # dropped save reads as lost work.
                            rank0_print(
                                "async checkpoint save failed before "
                                f"restart ({type(e).__name__}: {e}); "
                                "resuming from the previous complete "
                                "checkpoint"
                            )
                    s = restore_latest(_maybe_stack(
                        init_model_and_state(model, config=opt_config)
                    ))
                    if coordinator is not None:
                        coordinator.record_valid_step(
                            int(jax.device_get(s.step))
                        )
                    # Re-derive finished-epoch progress from what was
                    # actually RESTORED, never from the in-memory
                    # counter: if the newest complete checkpoint is
                    # older than the counter says (torn async save,
                    # kill mid-write), trusting the counter would
                    # silently drop the un-checkpointed epochs.
                    # Rounds down under guard-skipped steps — an epoch
                    # is re-run rather than skipped, which only costs
                    # time, not correctness.
                    progress["epochs"] = min(
                        args.epochs,
                        int(jax.device_get(s.step))
                        // max(args.max_iters, 1),
                    )
                wd = (
                    RaisingWatchdog(args.watchdog_timeout, events).start()
                    if args.watchdog_timeout
                    else None
                )
                try:
                    out, _ = run_epochs(s, wd)
                    return out
                finally:
                    if wd is not None:
                        wd.stop()

            state = run_attempts(
                attempt, max_restarts=args.max_restarts, events=events
            )
        else:
            state, _ = run_epochs(state, watchdog)
        run_completed = True
    finally:
        # Flush in finally so a crash/interrupt mid-run keeps the rows
        # already logged — the feature's main use is diagnosing bad runs.
        if watchdog is not None:
            # Disarm before the (potentially long) final async-save
            # flush — a blocking close() with no beats is not a stall.
            watchdog.stop()
        if coordinator is not None:
            # Clean completion must publish done=True (finish): a
            # frozen-but-not-done beat file reads as a death to peers
            # still in their run tail.  A failed run deliberately does
            # NOT publish done — the frozen file going stale is exactly
            # how the gang learns this rank died.
            if run_completed:
                coordinator.finish()
            else:
                coordinator.stop()
        if ckpt_writer is not None:
            # Don't exit with a half-written async save in flight.
            ckpt_writer.close()
        if preemption is not None:
            preemption.uninstall()
        if show_resilience:
            # Printed even on a crashed run (in finally): the counters
            # are the diagnosis — silent robustness is no robustness.
            from distributed_machine_learning_tpu.utils.summary import (
                resilience_summary,
            )

            rank0_print(resilience_summary(events))
        if metrics is not None:
            metrics.save(args.metrics_file)
            rank0_print(
                f"Wrote {metrics.count} metric rows to "
                f"{args.metrics_file}"
                + (" (streamed; append mode: prior runs' rows in the "
                   "same file are preserved above this run's)"
                   if metrics._sink is not None and metrics.append else
                   " (streamed)" if metrics._sink is not None else "")
            )
        if telemetry is not None:
            # Uninstall BEFORE close so late events (shutdown paths) hit
            # a closed sink never; then flush + terminate the trace.
            set_telemetry(prev_telemetry)
            telemetry.close()
            rank0_print(f"Telemetry written to {args.telemetry_dir}")
        ctx.shutdown()  # dist.destroy_process_group parity (part2/2a/main.py:207)
