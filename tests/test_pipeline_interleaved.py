"""Interleaved pipeline schedule: update-equivalence vs GPipe and the
layout round-trip.

The interleaved schedule computes the same function as GPipe with a
different (v-fold less bubbly) tick order and a permuted parameter
stacking — losses and updates must agree exactly, and v=1 must BE the
GPipe schedule.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.parallel.pipeline import (
    init_pipeline_state,
    make_pp_lm_train_step,
    microbatch,
    shard_pp_state,
    unstack_lm_params,
)
from distributed_machine_learning_tpu.parallel.pipeline_interleaved import (
    init_interleaved_state,
    make_pp_interleaved_lm_train_step,
    stack_interleaved,
    unstack_interleaved,
)
from distributed_machine_learning_tpu.runtime.mesh import make_mesh
from distributed_machine_learning_tpu.train.adamw import AdamWConfig


def _pipe_mesh(p=4):
    return make_mesh(p, axis_names=("pipe",))


def _model(n_layers=8):
    return TransformerLM(vocab_size=64, d_model=16, n_layers=n_layers,
                         n_heads=2, attn_impl="dense")


def _batch(batch=8, seq=12):
    rng = np.random.default_rng(23)
    toks = rng.integers(0, 64, (batch, seq + 1)).astype(np.int32)
    return jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])


def test_stack_roundtrip():
    model = _model()
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state

    plain = init_lm_state(model).params
    stacked = stack_interleaved(plain, 8, num_stages=4, v=2)
    back = unstack_interleaved(stacked, 8, num_stages=4, v=2)
    for (ka, a), (kb, b) in zip(
        sorted(jax.tree_util.tree_leaves_with_path(plain),
               key=lambda kv: str(kv[0])),
        sorted(jax.tree_util.tree_leaves_with_path(back),
               key=lambda kv: str(kv[0])),
    ):
        assert str(ka) == str(kb)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize(
    "m,v",
    [
        (4, 2),
        # Deep variants (each is its own serial XLA compile on the
        # 1-core box, ~10-14s apiece): the m=p keystone stays in the
        # default run, the multiple-of-P and ragged cases ride -m "".
        pytest.param(8, 2, marks=pytest.mark.slow),
        pytest.param(6, 2, marks=pytest.mark.slow),
    ],
    ids=["m=p", "m=2p", "m-ragged"],
)
def test_interleaved_matches_gpipe(m, v):
    """Same loss and updates as GPipe for M==P, M a multiple of P, and a
    ragged M (masked partial group)."""
    model = _model()
    mesh = _pipe_mesh(4)
    x, y = _batch(batch=24)
    xs, ys = microbatch(x[:m * 2], y[:m * 2], m)

    g_state = shard_pp_state(
        init_pipeline_state(model, config=AdamWConfig()), mesh)
    g_step = make_pp_lm_train_step(model, mesh, m)
    i_state = shard_pp_state(
        init_interleaved_state(model, 4, v, config=AdamWConfig()), mesh)
    i_step = make_pp_interleaved_lm_train_step(model, mesh, m, v)

    for _ in range(2):
        g_state, g_loss = g_step(g_state, xs, ys)
        i_state, i_loss = i_step(i_state, xs, ys)
        np.testing.assert_allclose(float(i_loss), float(g_loss),
                                   rtol=1e-5, atol=1e-6)

    g_plain = unstack_lm_params(
        jax.device_get(g_state.params), model.n_layers)
    i_plain = unstack_interleaved(
        jax.device_get(i_state.params), model.n_layers, 4, v)
    for k in g_plain:
        for a, b in zip(jax.tree_util.tree_leaves(i_plain[k]),
                        jax.tree_util.tree_leaves(g_plain[k])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=1e-6,
                                       err_msg=k)


def test_interleaved_v1_is_gpipe_layout():
    """v=1: the stacking is the plain contiguous-span order and the
    schedule degenerates to GPipe exactly (bitwise loss)."""
    model = _model(n_layers=4)
    mesh = _pipe_mesh(4)
    x, y = _batch()
    xs, ys = microbatch(x, y, 4)
    g_state = shard_pp_state(init_pipeline_state(model), mesh)
    g_step = make_pp_lm_train_step(model, mesh, 4)
    i_state = shard_pp_state(init_interleaved_state(model, 4, 1), mesh)
    i_step = make_pp_interleaved_lm_train_step(model, mesh, 4, 1)
    _, g_loss = g_step(g_state, xs, ys)
    _, i_loss = i_step(i_state, xs, ys)
    np.testing.assert_allclose(float(i_loss), float(g_loss), rtol=1e-6)


def test_interleaved_guards():
    model = _model(n_layers=8)
    mesh = _pipe_mesh(4)
    with pytest.raises(ValueError, match="chunks"):
        make_pp_interleaved_lm_train_step(model, mesh, 4, 3)  # 8 % 12
    with pytest.raises(ValueError, match=">= 1"):
        make_pp_interleaved_lm_train_step(model, mesh, 4, 0)
