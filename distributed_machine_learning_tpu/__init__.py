"""distributed_machine_learning_tpu — a TPU-native distributed-training framework.

A brand-new JAX/XLA/pjit/Pallas framework with the capabilities of the
reference ``Rishideep08/Distributed-Machine-Learning`` (a three-part
torch.distributed/gloo CIFAR-10 training assignment — see SURVEY.md):

- ``models/``    Flax model zoo: cfg-driven VGG-11/13/16/19 (reference
                 ``part1/model.py:3-8``) with optional BatchNorm, plus
                 ResNet-18/50 (BASELINE.json configs).
- ``data/``      CIFAR-10 pipeline without torchvision: pickle-batch parser,
                 device-side RandomCrop(32, pad=4)+flip augmentation, and
                 ``DistributedSampler(shuffle=False)``-compatible sharding
                 (reference ``part2/2a/main.py:158-167``).
- ``parallel/``  the pluggable gradient-sync layer — the reference's only
                 varying layer (SURVEY.md §1): ``none`` (part1),
                 ``gather_scatter`` (part2a), ``all_reduce`` (part2b),
                 ``ring`` (part3 north-star: bucketed lax.ppermute ring).
- ``ops/``       the collective building blocks: psum/pmean wrappers,
                 all-gather-based centralized sum, and the hand-rolled
                 bucketed ring reduce-scatter/all-gather on ``lax.ppermute``.
- ``train/``     jitted train/eval steps over a ``jax.sharding.Mesh`` via
                 ``shard_map``; SGD with torch-update semantics; the
                 40-iteration timing driver (reference ``part1/main.py:32-58``).
- ``runtime/``   multi-host bootstrap (``--master-ip/--rank/--num-nodes`` →
                 ``jax.distributed.initialize``), mesh construction, seeding.
- ``cli/``       the four entrypoints with the reference's flags kept verbatim.
- ``utils/``     timing harness, rank-0-gated logging, checkpointing.

Unlike the reference — four copy-pasted clones varying only in the sync
layer (SURVEY.md §1) — this is one shared core with the sync strategy as a
plug-in.
"""

__version__ = "0.1.0"

from distributed_machine_learning_tpu import utils  # noqa: F401
