"""Compressed-ring weak-scaling bench: wire bytes, step tails, parity.

Measures the round-7 tentpole (``ops/ring.py`` wire schemes +
``parallel/strategies.py::RingAllReduce`` error feedback) three ways,
per world size and codec:

- **wire bytes/step** — the static accounting
  (``ring_wire_bytes``; the HLO audit in ``overlap_audit.py
  --wire-bytes`` verifies the same number against the compiled
  program's collective-permute shapes);
- **step time p50/p95** — the mandatory-tail protocol (PERF.md round-6
  mandate).  NOTE on CPU hosts the ppermute "wire" is a memcpy, so
  compression costs compute and saves nothing — the honest reading of
  a CPU row is *overhead of the codec*, while the byte column is the
  bandwidth win an ICI-bound pod realizes;
- **loss parity** — final-loss relative delta vs the exact ring over
  the same fixed-seed synthetic batch stream (error feedback on).

Weak scaling: per-device batch is FIXED (default 16); the global batch
grows with the world, the reference's scaling protocol.

Run:  python -m distributed_machine_learning_tpu.bench.ring_compress \
          [--worlds 2,4,8] [--iters 24] [--model vggtest] [--json out]
"""

from __future__ import annotations

import argparse
import json
import time


def bench_ring_compress(worlds=(2, 4, 8), iters: int = 24,
                        per_device_batch: int = 16,
                        model_name: str = "vggtest",
                        topk_frac: float = 0.125,
                        bucket_mb: int = 25) -> list[dict]:
    import jax
    import numpy as np

    from distributed_machine_learning_tpu.cli.common import (
        SEED,
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.models.registry import get_model
    from distributed_machine_learning_tpu.ops.ring import WIRE_SCHEMES
    from distributed_machine_learning_tpu.parallel.strategies import (
        get_strategy,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh
    from distributed_machine_learning_tpu.train.sgd import SGDConfig
    from distributed_machine_learning_tpu.train.step import (
        make_train_step,
        shard_batch,
    )
    from distributed_machine_learning_tpu.utils.timing import (
        percentile_stats,
    )

    model = get_model(model_name, use_bn=False)
    rows = []
    for world in worlds:
        if world > jax.device_count():
            continue
        mesh = make_mesh(world)
        B = per_device_batch * world
        rng = np.random.default_rng(SEED)
        batches = [
            (rng.integers(0, 256, (B, 32, 32, 3), dtype=np.uint8),
             rng.integers(0, 10, B).astype(np.int32))
            for _ in range(iters)
        ]
        final_exact = None
        for compress in WIRE_SCHEMES:  # "none" first: the parity anchor
            kwargs = {"bucket_bytes": bucket_mb * 2**20}
            if compress != "none":
                kwargs.update(compress=compress, topk_frac=topk_frac)
            strategy = get_strategy("ring", **kwargs)
            state = init_model_and_state(
                model,
                config=SGDConfig(learning_rate=0.1, weight_decay=0.0),
            )
            n_elems = sum(
                int(l.size)
                for l in jax.tree_util.tree_leaves(state.params)
            )
            step = make_train_step(model, strategy, mesh=mesh,
                                   augment=False)
            times = []
            loss = None
            for i, (x, y) in enumerate(batches):
                xs, ys = shard_batch(mesh, x, y)
                t0 = time.perf_counter()
                state, loss = step(state, xs, ys)
                loss = jax.block_until_ready(loss)
                if i > 0:  # iteration 0 holds the compile
                    times.append(time.perf_counter() - t0)
            final = float(loss)
            if compress == "none":
                final_exact = final
            stats = percentile_stats(times)
            rows.append({
                "world": world,
                "global_batch": B,
                "compress": compress,
                "error_feedback": getattr(strategy, "stateful", False),
                "wire_bytes_per_step": strategy.wire_bytes_per_step(
                    n_elems, world
                ),
                "compression_ratio": strategy.compression_ratio(
                    n_elems, world
                ),
                "iter_p50_s": stats["p50"],
                "iter_p95_s": stats["p95"],
                "final_loss": final,
                "final_loss_rel_delta_vs_exact": (
                    None if final_exact is None
                    else abs(final - final_exact) / max(abs(final_exact),
                                                        1e-30)
                ),
            })
            print(json.dumps(rows[-1]))
    return rows


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--worlds", default="2,4,8")
    parser.add_argument("--iters", default=24, type=int)
    parser.add_argument("--batch-size", default=16, type=int,
                        help="PER-DEVICE batch (weak scaling)")
    parser.add_argument("--model", default="vggtest")
    parser.add_argument("--topk-frac", default=0.125, type=float)
    parser.add_argument("--bucket-mb", default=25, type=int)
    parser.add_argument("--json", dest="json_out", default=None)
    args = parser.parse_args(argv)
    rows = bench_ring_compress(
        worlds=tuple(int(w) for w in args.worlds.split(",")),
        iters=args.iters,
        per_device_batch=args.batch_size,
        model_name=args.model,
        topk_frac=args.topk_frac,
        bucket_mb=args.bucket_mb,
    )
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(rows, f, indent=2)
        print(f"wrote {args.json_out}")


if __name__ == "__main__":
    main()
