"""Optimizer registry: one table mapping name → (config class, update fn).

Single source of truth consumed by the train-step builder
(``train/step.py``), the CLI (``cli/common.py`` — flag choices and config
construction), and checkpoint restore (``train/checkpoint.py`` — config
class by saved name), so adding an optimizer is one entry here instead of
four coordinated edits.
"""

from __future__ import annotations

from distributed_machine_learning_tpu.train.lars import LARSConfig, lars_update
from distributed_machine_learning_tpu.train.sgd import SGDConfig, sgd_update

OPTIMIZERS = {
    "sgd": (SGDConfig, sgd_update),
    "lars": (LARSConfig, lars_update),
}


def optimizer_names() -> list[str]:
    return sorted(OPTIMIZERS)


def get_optimizer(name: str):
    """(config_class, update_fn) for ``name``; raises on unknown names."""
    try:
        return OPTIMIZERS[name]
    except KeyError:
        raise ValueError(
            f"unknown optimizer {name!r}; choose from {optimizer_names()}"
        ) from None


def config_class_by_name(class_name: str):
    """Config class by its __name__ (checkpoint restore)."""
    for cfg_cls, _ in OPTIMIZERS.values():
        if cfg_cls.__name__ == class_name:
            return cfg_cls
    raise ValueError(
        f"unknown optimizer config class in checkpoint: {class_name!r}"
    )
