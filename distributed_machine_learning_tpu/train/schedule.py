"""Learning-rate schedules and gradient transforms.

The reference trains with one fixed learning rate for its 40 iterations
(SGD lr=0.1 — ``part1/main.py:120-121``); ``SGDConfig``'s static default
replicates that.  Real training runs need the rate to move, so this
module adds the standard schedule family — as pure ``step -> lr``
functions of a traced step counter, so a schedule lives *inside* the
jitted train step: no host round-trip per step, no recompile per lr
value (the alternative — baking each lr into the static config — would
retrace the program every time the rate changed).

Gradient clipping follows the same design: a pure pytree → pytree
transform applied after gradient sync (clip the *global* gradient, the
DDP-semantics order) and before the SGD update.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def constant(lr: float):
    """The reference's behavior: fixed rate (part1/main.py:120)."""

    def schedule(step):
        del step
        return jnp.float32(lr)

    return schedule


def warmup_cosine(
    peak_lr: float,
    warmup_steps: int,
    total_steps: int,
    end_lr: float = 0.0,
):
    """Linear warmup 0 → peak over ``warmup_steps``, then cosine decay to
    ``end_lr`` at ``total_steps`` — the standard large-batch recipe."""
    if total_steps <= warmup_steps:
        raise ValueError(
            f"total_steps={total_steps} must exceed warmup_steps={warmup_steps}"
        )

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = peak_lr * step / max(warmup_steps, 1)
        progress = jnp.clip(
            (step - warmup_steps) / (total_steps - warmup_steps), 0.0, 1.0
        )
        cos = end_lr + 0.5 * (peak_lr - end_lr) * (1 + jnp.cos(jnp.pi * progress))
        return jnp.where(step < warmup_steps, warm, cos).astype(jnp.float32)

    return schedule


def step_decay(lr: float, boundaries: tuple[int, ...], gamma: float = 0.1):
    """Multiply the rate by ``gamma`` at each boundary step (the classic
    CIFAR/ImageNet staircase)."""
    bounds = jnp.asarray(sorted(boundaries), jnp.int32)

    def schedule(step):
        n_passed = jnp.sum(jnp.asarray(step, jnp.int32) >= bounds)
        return jnp.float32(lr) * jnp.float32(gamma) ** n_passed

    return schedule


def global_norm(tree) -> jax.Array:
    """fp32 global L2 norm of a pytree."""
    return jnp.sqrt(
        sum(
            jnp.sum(jnp.square(g.astype(jnp.float32)))
            for g in jax.tree_util.tree_leaves(tree)
        )
    )


def clip_by_global_norm(grads, max_norm: float):
    """Scale the gradient pytree so its global L2 norm is at most
    ``max_norm`` (fp32 norm arithmetic regardless of leaf dtype)."""
    norm = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return jax.tree_util.tree_map(lambda g: (g * scale).astype(g.dtype), grads)
