#!/usr/bin/env python3
"""Verify checkpoints against their manifests — stdlib only, no JAX.

Usage::

    python tools/ckpt_verify.py PATH [--quiet]

``PATH`` may be a single ``step_<n>`` checkpoint directory or any
directory containing them (a run's ``--ckpt-dir``, or a gang's
per-rank root ``.../ckpt/rank<r>/`` — the scan is recursive).  For each
checkpoint: completeness (state dir + config), the quarantine marker,
and every file's sha256 + byte size against ``manifest.json``
(``train/checkpoint.py`` writes it between the state dir and the config
file).  Prints per-file status and the per-leaf digest table the
manifest records (leaf *content* re-verification needs the array
runtime, so it happens at restore time — ``restore_checkpoint`` — not
here).  Exits nonzero on any mismatch, quarantined dir, or incomplete
checkpoint; legacy (pre-manifest) checkpoints report UNVERIFIABLE
without failing the run.

Deliberately dependency-free (hashlib + json + os): this is the tool an
operator runs on a storage node at 3am to decide whether a run can be
resumed, where the training environment may not even be installed.  The
on-disk format it checks is defined by ``train/checkpoint.py``; the two
must stay in sync.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys

CONFIG_FILE = "sgd_config.json"
STATE_DIR = "state"
MANIFEST_FILE = "manifest.json"
INVALID_MARKER = ".invalid"


def sha256_of(path: str) -> tuple[str, int]:
    h = hashlib.sha256()
    n = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            n += len(chunk)
            h.update(chunk)
    return h.hexdigest(), n


def find_step_dirs(root: str) -> list[str]:
    """Every ``step_<n>`` directory under ``root`` (or ``root`` itself),
    sorted by path then step for stable output."""
    root = os.path.abspath(root)
    name = os.path.basename(root)
    if name.startswith("step_") and name[5:].isdigit():
        return [root]
    found = []
    for dirpath, dirnames, _ in os.walk(root):
        for d in sorted(dirnames):
            if d.startswith("step_") and d[5:].isdigit():
                found.append(os.path.join(dirpath, d))
        # don't descend into checkpoints themselves
        dirnames[:] = [d for d in dirnames
                       if not (d.startswith("step_") and d[5:].isdigit())]
    return sorted(found, key=lambda p: (os.path.dirname(p),
                                        int(os.path.basename(p)[5:])))


def verify_step_dir(path: str, quiet: bool) -> tuple[bool, str]:
    """(ok, status line) for one checkpoint; prints detail unless quiet."""
    rel = path

    def emit(line: str) -> None:
        if not quiet:
            print(line)

    marker = os.path.join(path, INVALID_MARKER)
    if os.path.exists(marker):
        try:
            with open(marker) as f:
                reason = json.load(f).get("reason", "unknown")
        except (OSError, json.JSONDecodeError):
            reason = "unreadable marker"
        return False, f"QUARANTINED {rel}  ({reason})"
    complete = (os.path.isdir(os.path.join(path, STATE_DIR))
                and os.path.isfile(os.path.join(path, CONFIG_FILE)))
    if not complete:
        return False, f"INCOMPLETE  {rel}  (state dir or config missing)"
    manifest_path = os.path.join(path, MANIFEST_FILE)
    if not os.path.isfile(manifest_path):
        return True, f"UNVERIFIABLE {rel}  (legacy checkpoint: no manifest)"
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return False, f"BAD-MANIFEST {rel}  ({e})"

    bad = 0
    files = manifest.get("files", {})
    for relf, entry in sorted(files.items()):
        fp = os.path.join(path, relf)
        if not os.path.isfile(fp):
            emit(f"  MISSING  {relf}")
            bad += 1
            continue
        size = os.path.getsize(fp)
        if size != entry.get("bytes"):
            emit(f"  SIZE     {relf}  {size} != {entry.get('bytes')}")
            bad += 1
            continue
        sha, _ = sha256_of(fp)
        if sha != entry.get("sha256"):
            emit(f"  CORRUPT  {relf}  (sha256 mismatch)")
            bad += 1
    leaves = manifest.get("leaves", {})
    if leaves and not quiet:
        emit(f"  {len(files)} file(s) checked; recorded leaves:")
        width = max((len(n) for n in leaves), default=0)
        for name, entry in sorted(leaves.items()):
            if "sha256" not in entry:
                emit(f"    {name:<{width}}  "
                     f"UNVERIFIED ({entry.get('unverified', '?')})")
                continue
            shape = "x".join(str(d) for d in entry.get("shape", [])) or "()"
            status = "ok" if bad == 0 else "suspect"
            emit(f"    {name:<{width}}  {shape:>12}  "
                 f"{entry.get('dtype', '?'):>9}  "
                 f"{entry.get('bytes', 0):>10,}B  "
                 f"crc32={entry.get('crc32', 0):>10}  "
                 f"sha256={entry['sha256'][:12]}  [{status}]")
    if bad:
        return False, f"CORRUPT     {rel}  ({bad} bad file(s))"
    return True, (f"OK          {rel}  ({len(files)} files, "
                  f"{len(leaves)} leaves verified against manifest)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="verify checkpoint manifests (stdlib only)"
    )
    ap.add_argument("path", help="a step_<n> dir, or a directory "
                                 "containing them (scanned recursively)")
    ap.add_argument("--quiet", action="store_true",
                    help="one status line per checkpoint, no detail")
    args = ap.parse_args(argv)

    if not os.path.exists(args.path):
        print(f"ckpt_verify: no such path: {args.path}", file=sys.stderr)
        return 2
    dirs = find_step_dirs(args.path)
    if not dirs:
        print(f"ckpt_verify: no step_<n> checkpoints under {args.path}",
              file=sys.stderr)
        return 2
    failures = 0
    for d in dirs:
        ok, status = verify_step_dir(d, args.quiet)
        print(status)
        if not ok:
            failures += 1
    print(f"{len(dirs)} checkpoint(s), {failures} invalid")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
