from distributed_machine_learning_tpu.parallel.strategies import (
    SyncStrategy,
    NoSync,
    AllReduce,
    GatherScatter,
    RingAllReduce,
    get_strategy,
    STRATEGIES,
)

__all__ = [
    "SyncStrategy",
    "NoSync",
    "AllReduce",
    "GatherScatter",
    "RingAllReduce",
    "get_strategy",
    "STRATEGIES",
]
