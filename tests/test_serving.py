"""Elastic serving fleet (ISSUE 16): router, replica workers, and the
chaos-proven SLO campaigns.

Tier-1 keystones: ``test_chaos_kill_two_replicas_mid_load`` (the
flagship — 8 in-proc replicas + 2 warm spares, two killed under load;
the fleet must heal by promotion, keep p99 bounded, and deliver every
admitted request exactly once) and the graceful-drain campaign (a
drained replica finishes its in-flight work and demotes with zero
drops).  The subprocess-replica tcp variant with an injected partition
rides behind ``slow``.
"""

import importlib.util
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from distributed_machine_learning_tpu.runtime.faults import FaultEvents
from distributed_machine_learning_tpu.runtime.serving import (
    Overloaded,
    ServingConfig,
    ServingRouter,
)
from distributed_machine_learning_tpu.runtime.serving_worker import (
    ServingWorkerConfig,
    run_serving_worker,
    start_worker_thread,
)
from distributed_machine_learning_tpu.runtime.transport import (
    FileTransport,
    InProcHub,
    InProcTransport,
    TcpGangServer,
    TcpTransport,
)
from distributed_machine_learning_tpu.telemetry import Telemetry
from distributed_machine_learning_tpu.telemetry.registry import (
    Histogram,
    default_latency_buckets,
    default_time_buckets,
)
from distributed_machine_learning_tpu.telemetry.tracer import read_trace

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _step(prompts):
    return [list(p) + [sum(p) % 97] for p in prompts]


def _slow_step(delay_s):
    def step(prompts):
        time.sleep(delay_s)
        return _step(prompts)

    return step


# ---------------------------------------------------------------------------
# Router policy units (no fleet spawned)
# ---------------------------------------------------------------------------


def test_admission_control_rejects_loudly_past_the_bound():
    events = FaultEvents()
    router = ServingRouter(InProcTransport(InProcHub()),
                           ServingConfig(max_queue=2), events=events)
    router.submit([1])
    router.submit([2])
    with pytest.raises(Overloaded, match="queue full"):
        router.submit([3])
    # The rejection is counted, mirrored into FaultEvents — never a
    # silent drop.
    assert router.rejected == 1
    assert events.request_rejects == 1
    audit = router.audit()
    assert audit["admitted"] == 2 and audit["rejected"] == 1


def test_duplicate_rid_and_closed_router_are_refused():
    router = ServingRouter(InProcTransport(InProcHub()),
                           ServingConfig(max_queue=8))
    router.submit([1], rid="a")
    with pytest.raises(ValueError, match="duplicate rid"):
        router.submit([2], rid="a")
    router.close()
    with pytest.raises(Overloaded, match="closed"):
        router.submit([3])


def test_latency_buckets_resolve_millisecond_tails():
    """The ISSUE 16 bugfix: the train-step doubling grid
    (``default_time_buckets``) puts a whole millisecond-scale serving
    distribution inside one bucket, flattening p50 into p99; the √2
    latency preset resolves the tail."""
    old = Histogram("lat_old", (), buckets=default_time_buckets())
    new = Histogram("lat_new", (), buckets=default_latency_buckets())
    for _ in range(90):          # the body: 1.7 ms
        old.observe(1.7e-3)
        new.observe(1.7e-3)
    for _ in range(10):          # the tail: 3.0 ms
        old.observe(3.0e-3)
        new.observe(3.0e-3)
    qo, qn = old.quantiles(), new.quantiles()
    # Old grid: body and tail share the [1.6ms, 3.2ms] bucket — the
    # interpolated p50 drifts >30% off the true 1.7 ms and the p99/p50
    # separation collapses.
    assert qo["p50"] > 1.3 * 1.7e-3
    assert qo["p99"] < 1.5 * qo["p50"]
    # New grid: the body lands within 10% and the tail stays visible.
    assert abs(qn["p50"] - 1.7e-3) < 0.1 * 1.7e-3
    assert qn["p99"] > 1.5 * qn["p50"]
    # The router's histogram is built on the fixed preset.
    router = ServingRouter(InProcTransport(InProcHub()))
    assert router.latency.bounds == tuple(default_latency_buckets())


def test_straggler_replica_is_replaced_by_a_spare():
    """PR 6 replace semantics re-aimed at serving: a replica whose
    compute intervals stay >4x the fleet median for 3 consecutive
    judgments is demoted and a warm spare promoted in its place.

    ISSUE 17 moved the detector feed off the beat channel and onto the
    request event stream (the ``computed`` stage deltas — the shared
    ``serving_stage_samples`` code path), so this test fabricates
    completions with deterministic compute intervals instead of beats
    with service times."""
    hub = InProcHub()
    tx = InProcTransport(hub)
    events = FaultEvents()
    router = ServingRouter(
        InProcTransport(hub),
        ServingConfig(replicas=3, replica_timeout_s=60.0),
        events=events)
    for rank in range(4):
        tx.announce_join(rank, {"rank": rank, "spare": True,
                                "kind": "serving", "time": time.time()})
    router.pump()  # heal: promote 3 of the 4 spares
    assert sorted(router._replicas) == [0, 1, 2]
    for _ in range(9):
        router.submit([1, 2])
    router.pump()  # dispatch across the three replicas
    for rank in range(3):
        for req in tx.take_requests(rank, 8):
            # A deterministic compute interval in the stage record:
            # rank 2's is 10x the others' — the straggler signal.
            req["events"].append({
                "stage": "computed", "by": f"replica{rank}",
                "dt": 0.5 if rank == 2 else 0.05})
            assert tx.post_result(rank, req["epoch"], {
                "rid": req["rid"], "output": req["prompt"],
                "events": req["events"]})
    for _ in range(4):  # collect, then 3 consecutive judgments
        router.pump()
    assert router.evictions == 1
    assert events.replica_evictions == 1
    assert 2 not in router._replicas and 3 in router._replicas
    assert tx.read_serving(2)["role"] == "spare"
    kinds = [e.get("kind") for e in tx.read_health_events()]
    assert kinds.count("serve_promote") == 4  # 3 initial + the heal
    evict = [e for e in tx.read_health_events()
             if e.get("kind") == "serve_evict"]
    assert evict[0]["rank"] == 2 and "straggler" in evict[0]["why"]


def test_worker_promotion_restores_and_demotion_respares():
    """The replica state machine seen from the worker: spare announces
    ride the join channel with the prefetched step, promotion triggers
    exactly one O(restore) callback, retirement falls back to spare."""
    hub = InProcHub()
    router_tx, worker_tx = InProcTransport(hub), InProcTransport(hub)
    stop = threading.Event()
    restored = []
    t, out = start_worker_thread(
        worker_tx, 5, _step, stop,
        ServingWorkerConfig(heartbeat_interval=0.01),
        prefetch_fn=lambda: 42, on_restore=restored.append)
    deadline = time.monotonic() + 5.0
    while 5 not in router_tx.read_joins():
        assert time.monotonic() < deadline, "spare never announced"
        time.sleep(0.005)
    assert router_tx.read_joins()[5]["prefetched_step"] == 42
    router_tx.set_serving_role(5, "live")
    router_tx.push_request(5, {"rid": "q1", "prompt": [2, 3],
                               "epoch": 0})
    while not router_tx.take_results(8):
        assert time.monotonic() < deadline, "no result served"
        time.sleep(0.005)
    assert restored == [42]
    # Retire: the worker observes the role flip and re-announces.
    router_tx.retire_replica(5)
    router_tx.consume_join(5)
    while 5 not in router_tx.read_joins():
        assert time.monotonic() < deadline, "never re-spared"
        time.sleep(0.005)
    stop.set()
    t.join(5.0)
    assert out["restores"] == 1 and out["served"] == 1


def test_make_serving_step_seam_matches_generate():
    """The inference seam: ``make_serving_step`` wraps the batch-static
    decode program as ``step(prompts) -> outputs`` over ragged python
    token lists, grouping by length so each group is one batched call —
    and greedy outputs must match ``generate`` exactly."""
    import jax.numpy as jnp
    import numpy as np

    from distributed_machine_learning_tpu.inference.generate import (
        generate,
        make_serving_step,
    )
    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.train.lm_step import (
        init_lm_state,
    )

    model = TransformerLM(vocab_size=32, d_model=16, n_layers=2,
                          n_heads=2)
    params = init_lm_state(model).params
    step = make_serving_step(model, params, max_new_tokens=4)
    prompts = [[1, 2, 3], [4, 5], [6, 7, 8]]
    outs = step(prompts)
    assert [len(o) for o in outs] == [7, 6, 7]
    for p, o in zip(prompts, outs):
        assert o[:len(p)] == p
        assert all(isinstance(t, int) for t in o)
    # The length-3 group ran as ONE batched call and must agree with
    # the batch-static entry point row for row.
    want = generate(model, params,
                    jnp.asarray([prompts[0], prompts[2]], jnp.int32),
                    max_new_tokens=4)
    np.testing.assert_array_equal(np.asarray([outs[0], outs[2]]),
                                  np.asarray(want))
    assert step(prompts) == outs  # greedy: deterministic
    with pytest.raises(ValueError, match="empty prompt"):
        step([[1], []])


def test_late_result_after_requeue_is_not_redispatched():
    """REVIEW fix: a rid requeued by an eviction and then completed by
    the dead replica's late-collected result must NOT be dispatched
    again off the queue — re-dispatching a done rid reset it to
    "dispatched", drove the open count negative when the survivor
    answered too, failed the exactly-once audit, and hung wait_idle."""
    hub = InProcHub()
    tx = InProcTransport(hub)
    router = ServingRouter(
        InProcTransport(hub),
        ServingConfig(replicas=1, replica_timeout_s=60.0))
    tx.announce_join(0, {"rank": 0, "spare": True, "kind": "serving",
                         "time": time.time()})
    router.pump()
    assert sorted(router._replicas) == [0]
    rid = router.submit([1, 2])
    router.pump()  # dispatched to replica 0
    # Replica 0 serves the request, but BEFORE the router collects the
    # result it judges 0 dead and evicts it — requeueing the rid.
    reqs = tx.take_requests(0, 8)
    assert [r["rid"] for r in reqs] == [rid]
    assert tx.post_result(0, reqs[0]["epoch"],
                          {"rid": rid, "output": [9]}) is True
    with router._lock:
        router._evict_locked(0, "test: presumed dead", time.monotonic())
    assert router.result(rid)["state"] == "queued"
    # A survivor joins; the next pump collects the late result FIRST,
    # then must skip the stale queue entry instead of re-dispatching.
    tx.announce_join(1, {"rank": 1, "spare": True, "kind": "serving",
                         "time": time.time()})
    router.pump()
    assert router.result(rid)["state"] == "done"
    with router._lock:
        assert router._replicas[1].in_flight == set()
    assert tx.take_requests(1, 8) == []
    verdict = router.audit()
    assert verdict["exactly_once"], verdict
    assert verdict["completed"] == 1 and verdict["open"] == 0
    assert verdict["duplicates_discarded"] == 0
    assert router.wait_idle(1.0)


class _StaleReadTx(InProcTransport):
    """Forces the retired-and-re-promoted race deterministically: the
    first time the worker observes its rank live, retire + re-promote
    the rank and push a request stamped with the NEW epoch — then hand
    the worker the pre-retire (stale) view it just read."""

    def __init__(self, hub, admin, rank):
        super().__init__(hub)
        self._admin = admin
        self._rank = rank
        self._raced = False

    def read_serving(self, replica=None):
        state = super().read_serving(replica)
        if (not self._raced and replica == self._rank
                and state.get("role") == "live"):
            self._raced = True
            self._admin.retire_replica(self._rank)
            self._admin.set_serving_role(self._rank, "live")
            e = self._admin.read_serving(self._rank)["epoch"]
            self._admin.push_request(self._rank, {
                "rid": "z", "prompt": [1, 2], "epoch": e})
        return state


def test_worker_repushes_requests_stamped_with_a_newer_epoch():
    """REVIEW fix: rank retired and re-promoted between the worker's
    serving read and its take — the taken requests carry the NEW
    epoch.  The worker must push them back and rebind instead of
    running them under the stale bound (where every post is fenced and
    the requests strand in the new replica's in-flight set forever,
    since the rank keeps beating and is never evicted)."""
    hub = InProcHub()
    admin = InProcTransport(hub)
    worker_tx = _StaleReadTx(hub, admin, rank=4)
    stop = threading.Event()
    t, out = start_worker_thread(
        worker_tx, 4, _step, stop,
        ServingWorkerConfig(heartbeat_interval=0.01))
    deadline = time.monotonic() + 10.0
    while 4 not in admin.read_joins():
        assert time.monotonic() < deadline, "spare never announced"
        time.sleep(0.002)
    admin.set_serving_role(4, "live")  # epoch 0; the racer moves the
    # rank to epoch 1 on the worker's next serving read.
    results = []
    while not results:
        assert time.monotonic() < deadline, "request z never served"
        results = admin.take_results(8)
        time.sleep(0.002)
    stop.set()
    t.join(5.0)
    assert [r["rid"] for r in results] == ["z"]
    assert results[0]["epoch"] == 1  # served under the REBOUND epoch
    assert out["repushed"] == 1 and out["served"] == 1
    assert out["fenced"] == 0 and out["restores"] == 2


def test_completed_entries_compact_and_late_duplicates_classify():
    """REVIEW fix: the ledger retains at most ``retain_done`` completed
    entries (prompt/result payloads are dropped; counters keep the
    audit exact), and a very late duplicate for a compacted rid still
    counts as a duplicate, never an unknown result."""
    hub = InProcHub()
    tx = InProcTransport(hub)
    router = ServingRouter(
        InProcTransport(hub),
        ServingConfig(replicas=1, replica_timeout_s=60.0,
                      retain_done=3))
    tx.announce_join(0, {"rank": 0, "spare": True, "kind": "serving",
                         "time": time.time()})
    router.pump()
    rids = [router.submit([i]) for i in range(8)]
    deadline = time.monotonic() + 10.0
    while router.completed < 8:
        assert time.monotonic() < deadline, router.audit()
        router.pump()
        for req in tx.take_requests(0, 8):
            tx.post_result(0, req["epoch"],
                           {"rid": req["rid"], "output": [0]})
    with router._lock:
        assert len(router._ledger) == 3
    assert router.result(rids[0]) is None  # compacted away
    assert router.result(rids[-1])["state"] == "done"
    # A dead replica's very late duplicate for a compacted rid.
    tx.post_result(0, 0, {"rid": rids[0], "output": [0]})
    router.pump()
    verdict = router.audit()
    assert verdict["admitted"] == verdict["completed"] == 8
    assert verdict["compacted"] == 5
    assert verdict["exactly_once"], verdict
    assert verdict["duplicates_discarded"] == 1
    assert verdict["unknown_results"] == 0


# ---------------------------------------------------------------------------
# Tier-1 campaigns
# ---------------------------------------------------------------------------

CHAOS_BUDGET_S = 150.0


def _spawn_fleet(hub, world, step_fn, wcfg=None):
    """One worker thread per rank, each with its OWN kill switch."""
    wcfg = wcfg or ServingWorkerConfig(heartbeat_interval=0.02)
    fleet = []
    for rank in range(world):
        stop = threading.Event()
        t, out = start_worker_thread(InProcTransport(hub), rank,
                                     step_fn, stop, wcfg)
        fleet.append((rank, stop, t, out))
    return fleet


def _submit_with_backpressure(router, n, deadline_s=120.0):
    deadline = time.monotonic() + deadline_s
    rng = 12345
    for _ in range(n):
        rng = (1103515245 * rng + 12345) % (1 << 31)
        prompt = [1 + (rng >> s) % 13 for s in (3, 7)]
        while True:
            try:
                router.submit(prompt)
                break
            except Overloaded:
                assert time.monotonic() < deadline, (
                    "fleet stopped absorbing load under backpressure")
                time.sleep(0.002)


@pytest.mark.faultinject
def test_chaos_kill_two_replicas_mid_load(tmp_path):
    """The flagship SLO campaign: 8 live replicas + 2 warm spares under
    a 200-request load; two replicas are killed mid-load.  The fleet
    must evict them on beat staleness, promote both spares, re-dispatch
    the orphaned requests, and still deliver every admitted request
    exactly once with a bounded p99."""
    t_start = time.monotonic()
    hub = InProcHub(mirror_dir=str(tmp_path / "gang"))
    events = FaultEvents()
    router = ServingRouter(
        InProcTransport(hub),
        ServingConfig(replicas=8, max_queue=64, micro_batch=4,
                      replica_timeout_s=0.4, poll_s=0.002),
        events=events)
    fleet = _spawn_fleet(hub, world=10,
                         step_fn=_slow_step(0.002))
    stop_router = threading.Event()
    rt = threading.Thread(target=router.run, args=(stop_router,),
                          name="router", daemon=True)
    rt.start()
    try:
        # Phase 1: quarter of the load against the healthy fleet.
        _submit_with_backpressure(router, 50)
        deadline = time.monotonic() + 30.0
        while router.completed < 25 or len(router._replicas) < 8:
            assert time.monotonic() < deadline, "fleet never warmed up"
            time.sleep(0.01)
        with router._lock:
            victims = sorted(router._replicas)[:2]
        # Phase 2: kill two LIVE replicas, keep the load coming.
        for rank, stop, _, _ in fleet:
            if rank in victims:
                stop.set()
        _submit_with_backpressure(router, 150)
        assert router.wait_idle(60.0), router.audit()
    finally:
        verdict = router.close()
        stop_router.set()
        for _, stop, t, _ in fleet:
            stop.set()
            t.join(5.0)
        rt.join(5.0)
    elapsed = time.monotonic() - t_start
    # Exactly-once: 200 admitted, 200 completed, zero lost; a request
    # finished by a dying replica AND a survivor is one delivery plus
    # one counted duplicate.
    assert verdict["exactly_once"], verdict
    assert verdict["admitted"] == verdict["completed"] == 200
    assert verdict["unknown_results"] == 0
    # The two kills were healed by the two warm spares.
    assert verdict["evictions"] == 2
    assert events.replica_evictions == 2
    assert verdict["promotions"] == 10  # 8 initial + 2 heals
    with router._lock:
        live = sorted(router._replicas)
    assert len(live) == 8 and not set(victims) & set(live)
    # SLO: the p99 absorbs the ~0.4s eviction window but stays bounded.
    assert verdict["latency"]["p99"] < 5.0, verdict["latency"]
    assert elapsed < CHAOS_BUDGET_S, (
        f"serving chaos campaign took {elapsed:.1f}s (cap "
        f"{CHAOS_BUDGET_S}s, target <20s)")
    # The post-mortem serving view renders from the mirrored ledger.
    spec = importlib.util.spec_from_file_location(
        "gang_status", os.path.join(REPO, "tools", "gang_status.py"))
    tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tool)
    status = tool.collect(str(tmp_path / "gang"),
                          str(tmp_path / "no-telemetry"))
    rendered = tool.render(status)
    assert "Serving fleet" in rendered
    assert "exactly-once: PASS" in rendered


@pytest.mark.faultinject
def test_graceful_drain_finishes_inflight_with_zero_drops():
    """Redeploy protocol: drain one replica mid-load — it stops getting
    new work, finishes what it owns, and demotes to spare.  Nothing is
    dropped, nothing is duplicated, and the eviction counter stays at
    zero (a drain is not a failure)."""
    hub = InProcHub()
    events = FaultEvents()
    router = ServingRouter(
        InProcTransport(hub),
        ServingConfig(replicas=2, max_queue=32, micro_batch=2,
                      replica_timeout_s=5.0, poll_s=0.002),
        events=events)
    fleet = _spawn_fleet(hub, world=3, step_fn=_slow_step(0.002))
    stop_router = threading.Event()
    rt = threading.Thread(target=router.run, args=(stop_router,),
                          daemon=True)
    rt.start()
    try:
        _submit_with_backpressure(router, 20)
        deadline = time.monotonic() + 30.0
        while router.completed < 5:
            assert time.monotonic() < deadline, "fleet never served"
            time.sleep(0.01)
        with router._lock:
            target = sorted(router._replicas)[0]
        assert router.drain(target)
        assert not router.drain(target)  # idempotent: already draining
        _submit_with_backpressure(router, 20)
        assert router.wait_idle(30.0), router.audit()
        drain_deadline = time.monotonic() + 10.0
        while router.drains_done < 1:
            assert time.monotonic() < drain_deadline, "drain never done"
            time.sleep(0.01)
    finally:
        verdict = router.close()
        stop_router.set()
        for _, stop, t, _ in fleet:
            stop.set()
            t.join(5.0)
        rt.join(5.0)
    assert verdict["exactly_once"], verdict
    assert verdict["admitted"] == verdict["completed"] == 40
    assert verdict["drains"] == 1 and events.drains == 1
    assert verdict["evictions"] == 0
    tx = InProcTransport(hub)
    assert tx.read_serving(target)["role"] == "spare"
    demote = [e for e in tx.read_health_events()
              if e.get("kind") == "serve_demote"]
    assert demote and demote[0]["why"] == "drained"


@pytest.mark.faultinject
def test_cli_serve_inproc_smoke():
    """The launcher end-to-end: in-proc fleet, a mid-load drain, exit
    status = the exactly-once audit."""
    res = subprocess.run(
        [sys.executable, "-m",
         "distributed_machine_learning_tpu.cli.serve",
         "--replicas", "2", "--spares", "1", "--requests", "40",
         "--drain-after", "10", "--gang-transport", "inproc",
         "--timeout", "60"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert res.returncode == 0, res.stdout + res.stderr
    assert "exactly-once audit: PASS" in res.stdout
    assert "2 replicas + 1 spares over inproc" in res.stdout
    assert "1 drains" in res.stdout


# ---------------------------------------------------------------------------
# Slow campaign: subprocess replicas over tcp, with a partition
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.faultinject
def test_tcp_subprocess_replica_partition_is_healed(tmp_path):
    """The cross-process shape: replica workers are real subprocesses
    joined over tcp; one gets its channel severed by injected chaos.
    The router must evict it on beat staleness, promote the spare
    subprocess, and keep the load exactly-once."""
    server = TcpGangServer().start()
    addr = server.address
    cmd = [sys.executable, "-m",
           "distributed_machine_learning_tpu.cli.serve",
           "--role", "worker", "--address", addr,
           "--service-time", "0.005"]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    procs = [
        subprocess.Popen([*cmd, "--rank", "0"], env=env),
        # Rank 1's channel is severed after ~300 of its own transport
        # ops — comfortably after its promotion, while it serves.
        subprocess.Popen([*cmd, "--rank", "1", "--tx-chaos",
                          "partition@300"], env=env),
    ]
    events = FaultEvents()
    router = ServingRouter(
        TcpTransport(addr, backoff_s=0.01),
        ServingConfig(replicas=2, max_queue=32, micro_batch=2,
                      replica_timeout_s=1.0, poll_s=0.01),
        events=events)
    stop_router = threading.Event()
    rt = threading.Thread(target=router.run, args=(stop_router,),
                          daemon=True)
    rt.start()
    try:
        # Gate the load on BOTH subprocess replicas being live, so the
        # partition is guaranteed to hit a serving replica.
        deadline = time.monotonic() + 30.0
        while True:
            with router._lock:
                if sorted(router._replicas) == [0, 1]:
                    break
            assert time.monotonic() < deadline, "replicas never joined"
            time.sleep(0.02)
        _submit_with_backpressure(router, 60)
        # The warm spare joins mid-load, ready for the heal.
        procs.append(subprocess.Popen([*cmd, "--rank", "2"], env=env))
        _submit_with_backpressure(router, 60)
        assert router.wait_idle(90.0), router.audit()
        # The severed rank stops beating whenever its chaos fires; the
        # router must notice, evict, and heal back to 2 live.
        deadline = time.monotonic() + 30.0
        while True:
            with router._lock:
                live = sorted(router._replicas)
            if router.evictions >= 1 and live == [0, 2]:
                break
            assert time.monotonic() < deadline, (
                router.evictions, live)
            time.sleep(0.05)
    finally:
        verdict = router.close()
        stop_router.set()
        rt.join(5.0)
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)
        server.stop()
    assert verdict["exactly_once"], verdict
    assert verdict["admitted"] == verdict["completed"] == 120
    assert verdict["evictions"] >= 1  # the partitioned rank
    assert events.replica_evictions >= 1


# ---------------------------------------------------------------------------
# Request-scoped tracing + SLO observability (ISSUE 17)
# ---------------------------------------------------------------------------

# The documented happy-path journey (runtime/transport.py::SERVING_STAGES
# minus the failure stamps): what every completed record must show.
EXPECTED_JOURNEY = ["admitted", "queued", "dispatched", "taken",
                    "bound", "computed", "posted", "completed"]


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _observed_fleet(tmp_path, backend, world, step_fn, *,
                    replicas=2, replica_timeout_s=5.0):
    """Router + workers with instance-tagged telemetry, over the file
    or inproc (dir-mirrored) backend — both leave a readable gang dir
    for the offline tools."""
    gang = str(tmp_path / "gang")
    teldir = str(tmp_path / "telemetry")
    if backend == "inproc":
        hub = InProcHub(mirror_dir=gang)
        make_tx = lambda: InProcTransport(hub)  # noqa: E731
    else:
        os.makedirs(gang, exist_ok=True)
        make_tx = lambda: FileTransport(gang)  # noqa: E731
    router_tel = Telemetry(teldir, instance="router", enabled=True)
    worker_tels = [Telemetry(teldir, instance=f"replica{r}", enabled=True)
                   for r in range(world)]
    router = ServingRouter(
        make_tx(),
        ServingConfig(replicas=replicas, max_queue=64, micro_batch=2,
                      replica_timeout_s=replica_timeout_s, poll_s=0.002),
        telemetry=router_tel)
    fleet = []
    for rank in range(world):
        stop = threading.Event()
        t, out = start_worker_thread(
            make_tx(), rank, step_fn, stop,
            ServingWorkerConfig(heartbeat_interval=0.02),
            telemetry=worker_tels[rank])
        fleet.append((rank, stop, t, out))
    return gang, teldir, router, router_tel, worker_tels, fleet


def _teardown_fleet(router, rt, stop_router, fleet, router_tel,
                    worker_tels):
    verdict = router.close()
    stop_router.set()
    for _, stop, t, _ in fleet:
        stop.set()
        t.join(5.0)
    rt.join(5.0)
    router_tel.close()
    for tel in worker_tels:
        tel.close()
    return verdict


@pytest.mark.parametrize("backend", ["inproc", "file"])
def test_request_journey_lands_in_every_artifact_plane(tmp_path,
                                                       backend):
    """The ISSUE 17 acceptance path on both single-host backends: a
    served request's journey shows up (a) as the documented stage-event
    sequence in the ledger record, (b) as per-stage histograms in the
    router's registry snapshot, (c) in the offline serve_status
    renderings including --postmortem, and (d) as a merged Perfetto
    timeline with router + replica tracks and the request span on both
    sides of a flow link."""
    gang, teldir, router, router_tel, worker_tels, fleet = \
        _observed_fleet(tmp_path, backend, world=2, step_fn=_step)
    stop_router = threading.Event()
    rt = threading.Thread(target=router.run, args=(stop_router,),
                          daemon=True)
    rt.start()
    rids = []
    try:
        for i in range(8):
            rids.append(router.submit([1 + i, 2]))
        assert router.wait_idle(60.0), router.audit()
        records = [router.result(rid) for rid in rids]
    finally:
        verdict = _teardown_fleet(router, rt, stop_router, fleet,
                                  router_tel, worker_tels)
    assert verdict["exactly_once"], verdict
    assert verdict["admitted"] == verdict["completed"] == 8

    # (a) The ledger record carries the full documented journey, with
    # rank-local deltas only: dt is None exactly where the previous
    # stamp crossed a process boundary (DML001 — no cross-host deltas).
    for rec in records:
        stages = [e["stage"] for e in rec["events"]]
        assert stages == EXPECTED_JOURNEY, stages
        by_stage = {e["stage"]: e for e in rec["events"]}
        assert by_stage["admitted"]["dt"] is None   # first stamp ever
        assert by_stage["taken"]["dt"] is None      # crossed the wire
        for stage in ("queued", "dispatched", "bound", "computed",
                      "posted", "completed"):
            assert by_stage[stage]["dt"] >= 0.0, by_stage[stage]
        for stage in ("admitted", "queued", "dispatched", "completed"):
            assert by_stage[stage]["by"] == "router"
        worker_by = by_stage["taken"]["by"]
        assert worker_by in ("replica0", "replica1")
        for stage in ("bound", "computed", "posted"):
            assert by_stage[stage]["by"] == worker_by
        assert by_stage["dispatched"]["disp"] == 1
        assert by_stage["taken"]["disp"] == 1   # rides the payload tag
        for ev in rec["events"]:
            assert "_mono_last" not in ev and "_mono_by" not in ev

    # Router-clock stage intervals partition the end-to-end latency:
    # queued + dispatched + completed ≈ total (worker stages nest
    # INSIDE completed's dispatch round trip — summing all eight would
    # double-count).  Means are exact sums, so the tolerance is only
    # clock-read placement, not histogram interpolation.
    means = {s: h.sum / h.count
             for s, h in router._stage_hist.items() if h.count}
    router_clock = (means["queued"] + means["dispatched"]
                    + means["completed"])
    e2e = router.latency.sum / router.latency.count
    assert abs(router_clock - e2e) < 0.25 * e2e + 0.05, (means, e2e)
    sl = verdict["stage_latency"]
    p50_sum = sum(sl[s]["p50"]
                  for s in ("queued", "dispatched", "completed"))
    assert p50_sum < 4.0 * verdict["latency"]["p50"] + 0.05

    # (b) The registry snapshot streams the per-stage histograms.
    with open(os.path.join(teldir, "registry.router.json")) as f:
        reg = json.load(f)
    stage_rows = {h["labels"]["stage"]: h for h in reg["histograms"]
                  if h["name"] == "serving_stage_latency_s"}
    assert {"queued", "dispatched", "bound", "computed", "posted",
            "completed"} <= set(stage_rows)
    assert all(row["count"] == 8 for row in stage_rows.values())
    gauge_names = {g["name"] for g in reg["gauges"]}
    assert {"serving_queue_depth", "serving_inflight",
            "serving_replicas"} <= gauge_names

    # (c) serve_status renders the same story offline, from the dirs.
    serve_status = _load_tool("serve_status")
    status = serve_status.collect(gang, teldir)
    assert len(status["requests"]) == 8
    assert set(status["stages"]) >= {"computed", "completed"}
    assert [r["rank"] for r in status["replicas"]] == [0, 1]
    rendered = serve_status.render(status)
    assert "Per-stage latency" in rendered
    assert "Per-replica compute" in rendered
    pm = serve_status.render_postmortem(status, rids[0])
    assert pm is not None and f"Postmortem {rids[0]}" in pm
    for stage in EXPECTED_JOURNEY:
        assert stage in pm
    assert serve_status.render_postmortem(status, "no-such-rid") is None
    slo = serve_status.slo_replay(status["requests"], ["p99<=30s"],
                                  short_window_s=5.0, long_window_s=60.0,
                                  burn_threshold=2.0)
    assert slo["ok"] is True and slo["replayed"] == 8

    # (d) trace_merge fuses router + replica streams into named tracks
    # in their own pid block, with the request flow-linked by rid.
    trace_merge = _load_tool("trace_merge")
    merged, counts = trace_merge.merge_traces(teldir)
    assert set(counts) == {"router", "replica0", "replica1"}
    assert counts["router"] == 8
    events = merged["traceEvents"]
    base = trace_merge.SERVING_PID_BASE
    spans = [e for e in events
             if e.get("ph") == "X" and e.get("name") == "request"]
    for rid in rids:
        pids = {e["pid"] for e in spans if e["args"].get("rid") == rid}
        assert base in pids, f"{rid} missing its router span"
        assert pids & {base + 1, base + 2}, (
            f"{rid} missing its replica span")
    flows = [e for e in events if e.get("name") == "request_flow"]
    assert len(flows) == 2 * 8   # one s + one f per request
    meta = {e["pid"]: e["args"]["name"] for e in events
            if e.get("ph") == "M" and e["name"] == "process_name"}
    assert meta[base] == "serve router"
    assert meta[base + 1] == "serve replica 0"
    assert meta[base + 2] == "serve replica 1"


@pytest.mark.faultinject
def test_chaos_kill_replica_mid_compute_terminates_the_record(tmp_path):
    """ISSUE 17 chaos proof: a replica wedges mid-compute holding a
    dispatched request.  The router evicts it on beat staleness and the
    record shows the victim's leg TERMINATED — ``requeued`` after
    ``dispatched`` — then a single ``completed`` on the promoted
    survivor; the victim's own late post is fenced, and every replica
    trace span is closed with a terminal outcome."""
    t_start = time.monotonic()
    release = threading.Event()
    poison = [13, 13, 13]

    def step(prompts):
        if poison in [list(p) for p in prompts]:
            release.wait(30.0)
        return _step(prompts)

    gang, teldir, router, router_tel, worker_tels, fleet = \
        _observed_fleet(tmp_path, "inproc", world=3, step_fn=step,
                        replicas=2, replica_timeout_s=0.4)
    stop_router = threading.Event()
    rt = threading.Thread(target=router.run, args=(stop_router,),
                          daemon=True)
    rt.start()
    try:
        deadline = time.monotonic() + 30.0
        while True:
            with router._lock:
                if len(router._replicas) == 2:
                    break
            assert time.monotonic() < deadline, "fleet never warmed up"
            time.sleep(0.005)
        rid = router.submit(poison)
        for i in range(10):
            router.submit([1 + i])
        deadline = time.monotonic() + 30.0
        while router.evictions < 1:
            assert time.monotonic() < deadline, (
                "stalled replica never evicted")
            time.sleep(0.005)
        release.set()   # un-wedge: survivors serve the requeued work
        assert router.wait_idle(60.0), router.audit()
        rec = router.result(rid)
    finally:
        release.set()
        verdict = _teardown_fleet(router, rt, stop_router, fleet,
                                  router_tel, worker_tels)
    assert verdict["exactly_once"], verdict
    assert verdict["admitted"] == verdict["completed"] == 11
    assert verdict["evictions"] == 1

    # The poisoned request's record: dispatched -> requeued (victim's
    # leg terminated by the router) -> dispatched again -> completed
    # ONCE, with the second leg's worker stamps from a different rank.
    stages = [e["stage"] for e in rec["events"]]
    first_disp = stages.index("dispatched")
    requeue_at = stages.index("requeued")
    assert first_disp < requeue_at, stages
    assert stages.count("dispatched") >= 2
    assert stages.count("completed") == 1
    assert stages.index("completed") > requeue_at
    requeue_ev = rec["events"][requeue_at]
    assert requeue_ev["by"] == "router"
    victim = requeue_ev["replica"]
    assert victim is not None
    serving_leg = [e for e in rec["events"] if e["stage"] == "computed"]
    assert serving_leg and all(
        e["by"] != f"replica{victim}" for e in serving_leg)
    # The requeue interval reached the stage histograms.
    assert verdict["stage_latency"].get("requeued", {}).get("count", 0) \
        or "requeued" in verdict["stage_latency"]

    # No unclosed spans: every request span in every replica trace is a
    # complete event with a terminal outcome — including the victim's
    # fenced late post.
    outcomes = []
    for r in range(3):
        path = os.path.join(teldir, f"trace.replica{r}.json")
        if not os.path.exists(path):
            continue
        for e in read_trace(path):
            if isinstance(e, dict) and e.get("name") == "request":
                assert e.get("ph") == "X" and e.get("dur", -1) >= 0
                stage = (e.get("args") or {}).get("stage")
                assert stage in ("posted", "fenced", "requeued"), e
                outcomes.append((e["args"].get("rank"), stage))
    assert (victim, "fenced") in outcomes, outcomes

    # The postmortem renders the full story from the mirrored ledger.
    serve_status = _load_tool("serve_status")
    pm = serve_status.render_postmortem(
        serve_status.collect(gang, teldir), rid)
    assert pm is not None and "requeued" in pm and "completed" in pm
    elapsed = time.monotonic() - t_start
    assert elapsed < CHAOS_BUDGET_S, (
        f"mid-compute chaos took {elapsed:.1f}s (cap {CHAOS_BUDGET_S}s)")


@pytest.mark.faultinject
def test_cli_serve_slo_verdict_gates_exit_status(tmp_path):
    """--slo end to end: a generous objective passes (rc 0) and leaves
    the telemetry artifacts; an impossible objective over deliberately
    slow service prints a failing verdict and exits 1."""
    base = [sys.executable, "-m",
            "distributed_machine_learning_tpu.cli.serve",
            "--replicas", "2", "--spares", "0", "--requests", "30",
            "--gang-transport", "inproc", "--timeout", "60"]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    teldir = str(tmp_path / "tel")
    ok = subprocess.run(
        [*base, "--telemetry-dir", teldir, "--slo", "p99<=30s",
         "--slo", "reject_ratio<=50%"],
        capture_output=True, text=True, timeout=120, env=env)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    assert "exactly-once audit: PASS" in ok.stdout
    assert "slo p99<=30s: PASS" in ok.stdout
    assert "slo verdict: PASS" in ok.stdout
    assert os.path.exists(os.path.join(teldir, "registry.router.json"))
    assert os.path.exists(os.path.join(teldir, "trace.router.json"))
    assert os.path.exists(os.path.join(teldir, "trace.replica0.json"))

    bad = subprocess.run(
        [*base, "--service-time", "0.02", "--slo", "p99<=1ms"],
        capture_output=True, text=True, timeout=120, env=env)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "exactly-once audit: PASS" in bad.stdout  # delivery still ok
    assert "slo p99<=1ms: FAIL" in bad.stdout
    assert "slo verdict: FAIL" in bad.stdout
    assert "SLO objectives violated" in bad.stderr
