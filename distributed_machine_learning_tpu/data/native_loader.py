"""ctypes bridge to the C++ prefetching batch loader (native/dataloader.cc).

The reference's host data path is torch's C++ DataLoader with
``pin_memory=True`` (``part2/2a/main.py:162-167``); this is its TPU-native
counterpart — batch assembly and prefetch run in a C++ worker thread
behind a bounded queue, so host gather overlaps device compute without
the GIL in the way.  The shared library is compiled from source on first
use with the system ``g++`` (no pip deps); when no toolchain is
available, callers fall back to the pure-Python loaders (same batch
stream — ``tests/test_native_loader.py`` asserts byte equality).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Iterator

import numpy as np

from distributed_machine_learning_tpu.data.cifar10 import Dataset
from distributed_machine_learning_tpu.data.sharding import shard_indices

_SRC = Path(__file__).resolve().parent.parent / "native" / "dataloader.cc"
_BUILD_DIR = _SRC.parent / "_build"
_LIB_PATH = _BUILD_DIR / "libdml_loader.so"

_lib = None
_lib_error: str | None = None
_lib_lock = threading.Lock()


def _compile() -> None:
    _BUILD_DIR.mkdir(exist_ok=True)
    tmp = _LIB_PATH.with_suffix(f".{os.getpid()}.tmp")
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        str(_SRC), "-o", str(tmp),
    ]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(tmp, _LIB_PATH)  # atomic: parallel builders race benignly


def _load():
    """Compile (once) and load the shared library; cache the outcome."""
    global _lib, _lib_error
    with _lib_lock:
        if _lib is not None or _lib_error is not None:
            return _lib
        try:
            if not _LIB_PATH.exists() or (
                _SRC.stat().st_mtime > _LIB_PATH.stat().st_mtime
            ):
                _compile()
            lib = ctypes.CDLL(str(_LIB_PATH))
            lib.dl_create.restype = ctypes.c_void_p
            lib.dl_create.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64,
            ]
            lib.dl_next.restype = ctypes.c_int64
            lib.dl_next.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ]
            lib.dl_destroy.restype = None
            lib.dl_destroy.argtypes = [ctypes.c_void_p]
            _lib = lib
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            _lib_error = f"native loader unavailable: {detail}"
        return _lib


def native_available() -> bool:
    return _load() is not None


def native_unavailable_reason() -> str | None:
    _load()
    return _lib_error


class NativeBatchLoader:
    """Drop-in for ``loader.BatchLoader`` backed by the C++ worker."""

    def __init__(
        self,
        dataset: Dataset,
        batch_size: int,
        indices: np.ndarray | None = None,
        prefetch: int = 4,
    ):
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        lib = _load()
        if lib is None:
            raise RuntimeError(_lib_error)
        self._lib = lib
        # Contiguous copies pinned to this object: the C++ side reads these
        # buffers for the lifetime of every handle created in __iter__.
        self._images = np.ascontiguousarray(dataset.images, dtype=np.uint8)
        self._labels = np.ascontiguousarray(dataset.labels, dtype=np.int32)
        self._indices = np.ascontiguousarray(
            np.arange(len(dataset)) if indices is None else indices,
            dtype=np.int64,
        )
        self.batch_size = batch_size
        self.prefetch = prefetch
        self._row_bytes = int(np.prod(self._images.shape[1:]))
        self._row_shape = self._images.shape[1:]

    def __len__(self) -> int:
        return (len(self._indices) + self.batch_size - 1) // self.batch_size

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        handle = self._lib.dl_create(
            self._images.ctypes.data, self._labels.ctypes.data,
            self._row_bytes, self._indices.ctypes.data, len(self._indices),
            self.batch_size, self.prefetch,
        )
        if not handle:
            raise RuntimeError("dl_create failed (bad arguments)")
        try:
            while True:
                out_i = np.empty((self.batch_size, *self._row_shape), np.uint8)
                out_l = np.empty((self.batch_size,), np.int32)
                rows = self._lib.dl_next(
                    handle, out_i.ctypes.data, out_l.ctypes.data
                )
                if rows == 0:
                    return
                yield out_i[:rows], out_l[:rows]
        finally:
            self._lib.dl_destroy(handle)


class NativeDistributedBatchLoader(NativeBatchLoader):
    """Drop-in for ``distributed_loader.DistributedBatchLoader``: same
    rank-major global-batch layout (derived from the same
    ``shard_indices`` source of truth), assembled by the C++ worker."""

    def __init__(
        self,
        dataset: Dataset,
        per_rank_batch: int,
        num_ranks: int,
        prefetch: int = 4,
    ):
        if per_rank_batch <= 0 or num_ranks <= 0:
            raise ValueError(
                f"per_rank_batch and num_ranks must be positive, got "
                f"{per_rank_batch}, {num_ranks}"
            )
        rank_indices = np.stack(
            [shard_indices(len(dataset), r, num_ranks) for r in range(num_ranks)]
        )  # [num_ranks, per_rank_count]
        steps = rank_indices.shape[1] // per_rank_batch  # drop_last=True
        b = per_rank_batch
        epoch = np.concatenate(
            [
                rank_indices[:, s * b : (s + 1) * b].reshape(-1)
                for s in range(steps)
            ]
        ) if steps else np.empty((0,), np.int64)
        super().__init__(
            dataset, b * num_ranks, indices=epoch, prefetch=prefetch
        )
        self.per_rank_batch = per_rank_batch
        self.num_ranks = num_ranks
        self.global_batch = b * num_ranks
