"""Shared scan-epoch timing harness for the benchmark entrypoints.

One copy of the measurement protocol (bench.py and bench/sweep.py both
use it): all timed iterations run as ONE jitted ``lax.scan`` over
pre-staged device-resident batches, and timing brackets a HOST VALUE
FETCH of the final loss.  Rationale — per-step Python dispatch would
dominate on a remote/tunneled device (~100 ms round-trip vs a ~4 ms
step), and an asynchronously-dispatched backend can return from
``block_until_ready`` before compute actually finishes, so only a value
fetch is trustworthy; the reference's excluded iteration 0
(``part1/main.py:53-58``) maps to the excluded compile run.
"""

from __future__ import annotations

import time

import jax
import numpy as np


def two_point_fit(timed, chain: int) -> float:
    """Per-dispatch seconds from a two-point fit: ``timed(n)`` measures n
    back-to-back dispatches + one host fetch; the slope between the
    1-dispatch and chain-dispatch measurements cancels the constant
    tunnel round-trip.  Shared by bench.py and bench_lm.py so the
    methodology cannot diverge.

    Guards both sides: RTT jitter can make the slope exceed the chained
    average (impossible physically — take the min) or go non-positive
    (slow RTT on t1, fast on tk — fall back to the overhead-inclusive
    chained average rather than report a negative time)."""
    t1 = timed(1)
    if chain <= 1:
        return t1
    tk = timed(chain)
    slope = (tk - t1) / (chain - 1)
    if slope <= 0:
        return tk / chain
    return min(slope, tk / chain)


def length_slope_fit(timed, n1: int, n2: int) -> float:
    """Per-unit seconds from measurements at two WORK SIZES ``n1 < n2``
    (scan lengths, generation lengths): slope ``(t2−t1)/(n2−n1)``
    cancels every size-independent cost (dispatch RTT, prefill,
    compile-warm residue).  Jitter guard mirrors :func:`two_point_fit`:
    an impossible slope falls back to the overhead-inclusive average
    ``t2/n2``."""
    if not 0 < n1 < n2:
        raise ValueError(f"need 0 < n1 < n2, got ({n1}, {n2})")
    t1 = timed(n1)
    t2 = timed(n2)
    slope = (t2 - t1) / (n2 - n1)
    avg = t2 / n2
    return avg if slope <= 0 else min(slope, avg)


def cast_serving_params(params, dtype):
    """Serving cast (f32 leaves only → ``dtype``) — one definition for
    every bench's target and draft params."""
    return jax.tree_util.tree_map(
        lambda p: p.astype(dtype) if p.dtype == jax.numpy.float32 else p,
        params,
    )


def prepare_serving_params(master, quant: str | None, dtype=None):
    """The serving param pipeline every decode bench shares: int8
    quantization from the f32 master params (``quant="int8"``), or the
    compute-dtype cast.  One copy (bench_lm.py, bench/spec_trained.py)
    so the benches can never measure different pipelines."""
    if quant == "int8":
        from distributed_machine_learning_tpu.ops.quant import (
            quantize_lm_params,
        )

        return quantize_lm_params(master)
    return cast_serving_params(
        master, dtype if dtype is not None else jax.numpy.bfloat16
    )


def interleaved_ab(run_one: dict, iters: int, warmup: int = 1) -> dict:
    """The interleaved A/B measurement protocol (grown as round 11's
    ``--selector-ab``; one copy here so every A/B bench cancels drift
    the same way).

    ``run_one`` maps config name → ``fn(round_idx)`` running ONE
    complete iteration of that config INCLUDING the host sync (block
    on the value) — the function is the timed unit.  Each round runs
    one iteration of EVERY config back-to-back, so the 1-core host's
    ±5% sequential drift (thermal, scheduler, page cache) lands on all
    configs equally and cancels in the comparison instead of
    masquerading as a config cost — the failure mode of timing config
    A's block and then config B's block.  ``warmup`` rounds run
    untimed first (compile lands there, the reference's excluded
    iteration 0).

    Returns ``{name: [seconds, ...]}`` with ``iters`` timed samples
    per config, in round order.
    """
    times: dict = {k: [] for k in run_one}
    for r in range(warmup):
        for fn in run_one.values():
            fn(r)
    for r in range(iters):
        for k, fn in run_one.items():
            t0 = time.perf_counter()
            fn(r)
            times[k].append(time.perf_counter() - t0)
    return times


def two_point_dispatch(dispatch, fetch, reps: int, chain: int) -> float:
    """The decode benches' shared timing harness: best-of-``reps`` over
    n chained dispatches closed by one host fetch, per-dispatch seconds
    via :func:`two_point_fit` (cancels the tunnel RTT)."""

    def timed(n_dispatches):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            out = None
            for _ in range(n_dispatches):
                out = dispatch()
            fetch(out)
            best = min(best, time.perf_counter() - t0)
        return best

    return two_point_fit(timed, chain)


def timed_scan_epoch(step, state, imgs, lbls, reps: int = 1, chain: int = 1,
                     stats: dict | None = None):
    """Time ``len(imgs)`` train steps as one compiled scan.

    ``step``: un-jitted ``(state, x, y) -> (state, loss)`` (build with
    ``make_train_step(..., jit=False)``).  ``imgs``/``lbls``: stacked
    [T, ...] device arrays, one leading slice per iteration.  Runs once
    untimed (compile, the reference's iteration 0), then ``reps`` timed
    runs; returns ``(best_seconds, final_loss, state)``.

    ``chain > 1`` measures by a two-point fit: each timed measurement
    still brackets dispatch + one host value fetch, but a second set of
    measurements enqueues ``chain`` back-to-back dispatches of the SAME
    epoch (every run starts from the untouched initial state, so the
    numerics of each are identical to the canonical single run — no
    1000-step divergence) before the single fetch, and the per-scan time
    is the slope ``(t_chain - t_1) / (chain - 1)``.  The constant tunnel
    round-trip (tens of ms on a remote chip, run-to-run variable — the
    r01 bench's 17% swing) cancels in the subtraction, leaving pure
    device time per 39-step scan.  The reference's own protocol has no
    such overhead to exclude — its timer wraps on-node compute only
    (part1/main.py:53-58).

    ``stats``: optional dict, filled in place with the tail of the raw
    measurements — ``p50_s``/``p95_s``/``p99_s``/``max_s`` per-scan
    seconds plus ``samples`` — so bench result dicts report tail
    latency alongside the best (BENCH_*.json rounds must carry p95 with
    the mean; docs/PERF.md).  Computed over the LONGEST-chain regime
    only: the 1-dispatch measurements each carry a full tunnel RTT that
    the chained ones amortize chain-fold, so pooling the regimes would
    make "p95" measure the RTT the two-point fit exists to cancel, not
    step stragglers.

    Raises ``RuntimeError`` on a non-finite final loss — a benchmark
    number from a diverged run must never be reported.
    """

    @jax.jit
    def run(state, imgs, lbls):
        def body(st, xy):
            st, loss = step(st, *xy)
            return st, loss

        return jax.lax.scan(body, state, (imgs, lbls))

    state0 = state
    out_state, losses = run(state0, imgs, lbls)
    final_loss = float(losses[-1])  # compile + completion
    if not np.isfinite(final_loss):
        raise RuntimeError(
            f"benchmark run diverged (final loss {final_loss}); refusing to "
            "report a throughput number"
        )

    samples: list[tuple[int, float]] = []  # (chain length, per-scan s)

    def timed(n_dispatches):
        """Best-of-reps seconds for n async same-epoch dispatches + 1 fetch."""
        best = float("inf")
        for _ in range(max(reps, 1)):
            start = time.perf_counter()
            for _ in range(n_dispatches):
                _, losses = run(state0, imgs, lbls)
            float(losses[-1])  # forces real device completion of the queue
            elapsed = time.perf_counter() - start
            samples.append((n_dispatches, elapsed / n_dispatches))
            best = min(best, elapsed)
        return best

    best = two_point_fit(timed, chain)
    if stats is not None:
        from distributed_machine_learning_tpu.utils.timing import (
            percentile_stats,
        )

        # Longest-chain regime only (see docstring): at chain=1 this is
        # the single regime, overhead-inclusive by necessity.
        longest = max(n for n, _ in samples)
        per_scan = [s for n, s in samples if n == longest]
        tail = percentile_stats(per_scan)
        stats.update(
            p50_s=tail["p50"], p95_s=tail["p95"], p99_s=tail["p99"],
            max_s=tail["max"], samples=len(per_scan),
        )
    return best, final_loss, out_state
