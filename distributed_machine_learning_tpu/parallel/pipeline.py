"""Pipeline parallelism: GPipe-style SPMD pipeline over a ``pipe`` mesh axis.

Capability beyond the reference (PP absent — SURVEY.md §2.3), built the
TPU way: transformer blocks are *stacked* along a leading layer axis and
that axis is sharded over the mesh, so every device holds a contiguous
span of layers.  The schedule is a single SPMD loop: each tick, every
device applies its span to its current microbatch activation, then the
activations rotate one hop along the ring via ``lax.ppermute``.  Stage 0
injects a fresh embedded microbatch per tick; the last stage peels off
finished microbatches into the loss.  After ``M + P − 1`` ticks all ``M``
microbatches have flowed through all ``P`` stages.

The backward pass needs no hand-written schedule: the transpose of
``ppermute`` is the reverse ``ppermute``, so ``jax.grad`` of this loop IS
the reverse pipeline, with XLA free to overlap the per-tick collective
with the neighboring stage compute.

What grad-of-scan FIXES, though, is the schedule: all forwards complete
before any backward starts (GPipe), so a stage holds (or remats) every
microbatch's activations at once — O(M) memory that caps how many
microbatches can amortize the (P−1)/(M+P−1) bubble.  Two sibling
schedules attack the two costs separately: 1F1B
(``parallel/pipeline_1f1b.py``, the CLI default) hand-writes the
one-backward-per-forward tick order to cut activation memory to O(P),
and the interleaved schedule (``parallel/pipeline_interleaved.py``,
``--pp-schedule interleaved``) gives each device v virtual stages to
cut the bubble itself to (P−1)/(v·M+P−1).  This module remains the
jax.grad-schedule reference both are property-tested against.

Parameter layout inside ``shard_map``:
  - ``blocks``: every Block param stacked to ``[n_layers, ...]``, sharded
    ``P("pipe", ...)`` → local ``[n_layers/P, ...]``, consumed by
    ``lax.scan`` (static shapes, one compiled block body per device);
  - ``embed`` / ``ln_f`` / ``lm_head``: replicated; only one stage's
    contribution is non-zero, so their gradients are ``psum``-ed over the
    pipe axis (the zero shares from other stages are free).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_machine_learning_tpu.models.transformer import Block, TransformerLM
from distributed_machine_learning_tpu.train.losses import lm_cross_entropy
from distributed_machine_learning_tpu.train.optimizers import (
    moment_layout as _moment_layout,
    update_fn_for_config,
)
from distributed_machine_learning_tpu.train.state import TrainState
from distributed_machine_learning_tpu.runtime.mesh import (
    shard_map_no_check as _shard_map,
)

PIPE_AXIS = "pipe"


def _block_module(model: TransformerLM) -> Block:
    # Flash passes through for the pipeline steps.  Pure pipeline: the
    # shard_map is FULLY manual over the pipe axis, so the Pallas call
    # sees local [mb, L] shapes natively (flash_mesh stays None).  The
    # 3-D step (partial-manual: batch/model automatic) sets flash_mesh +
    # flash_manual_axes on its model clone, and the wrap manualizes the
    # remaining axes from inside the pipe-manual region (parallel3d.py).
    return Block(
        n_heads=model.n_heads,
        d_ff=model.d_ff or 4 * model.d_model,
        attn_impl="flash" if model.attn_impl == "flash" else "dense",
        seq_axis=model.seq_axis,
        compute_dtype=model.compute_dtype,
        n_kv_heads=model.n_kv_heads,
        flash_mesh=model.flash_mesh,
        flash_batch_axis=model.flash_batch_axis,
        flash_head_axis=model.flash_head_axis,
        flash_manual_axes=model.flash_manual_axes,
        # The selective remat policy lives INSIDE the block (LN2+MLP
        # checkpointed, attention residuals saved), so the pipeline
        # honors it here; the "block" policy is applied by
        # _apply_local_span's whole-layer jax.checkpoint instead — see
        # _whole_layer_remat.
        remat_mlp=model.remat and model.remat_policy == "mlp",
    )


def _whole_layer_remat(model: TransformerLM) -> bool:
    """True when the pipeline span scan should wrap each layer in
    ``jax.checkpoint`` — i.e. ``remat=True`` under the whole-block
    policy.  The selective "mlp" policy checkpoints inside the Block
    (``_block_module``) and must NOT also be wrapped here, or the outer
    checkpoint would re-run attention anyway, silently downgrading the
    policy the user asked for."""
    return model.remat and model.remat_policy == "block"


def stack_lm_params(params: dict, n_layers: int) -> dict:
    """TransformerLM params (block_0..block_{n-1} dicts) → pipeline layout
    (one ``blocks`` tree with leading layer axis)."""
    blocks = [params[f"block_{i}"] for i in range(n_layers)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *blocks)
    return {
        "embed": params["embed"],
        "blocks": stacked,
        "ln_f": params["ln_f"],
        "lm_head": params["lm_head"],
    }


def unstack_lm_params(pipeline_params: dict, n_layers: int) -> dict:
    """Inverse of ``stack_lm_params`` (for checkpoint interop/tests)."""
    out = {
        "embed": pipeline_params["embed"],
        "ln_f": pipeline_params["ln_f"],
        "lm_head": pipeline_params["lm_head"],
    }
    for i in range(n_layers):
        out[f"block_{i}"] = jax.tree_util.tree_map(
            lambda x, i=i: x[i], pipeline_params["blocks"]
        )
    return out


def init_pipeline_state(model: TransformerLM, seed: int = 69143,
                        config=None) -> TrainState:
    """Initialize TransformerLM params (dense path) and restack them.
    ``config``: optional optimizer config (as in ``init_lm_state``)."""
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state

    state = init_lm_state(model, seed=seed, config=config)
    return TrainState.create(
        params=stack_lm_params(state.params, model.n_layers),
        rng=state.rng,
        config=state.config,
    )


def _apply_local_span(block: Block, stacked_local, x, positions,
                      remat: bool = False):
    """Run this device's span of layers over x via lax.scan.

    ``remat=True`` wraps each layer application in ``jax.checkpoint``:
    the backward pipeline then recomputes block activations instead of
    holding every (tick × layer) activation live — the memory term that
    otherwise scales with microbatch count under grad-of-scan."""

    def apply_layer(layer_params, h):
        return block.apply({"params": layer_params}, h, positions)

    if remat:
        apply_layer = jax.checkpoint(apply_layer)

    def body(h, layer_params):
        return apply_layer(layer_params, h), None

    h, _ = lax.scan(body, x, stacked_local)
    return h


def _pipeline_forward_loss(
    model: TransformerLM,
    params: dict,
    tokens_mb,  # [M, mb, L] int32 (replicated)
    targets_mb,  # [M, mb, L] int32
    *,
    pipe_axis: str,
    num_stages: int,
):
    """Forward + loss for all microbatches through the SPMD pipeline."""
    import flax.linen as nn

    block = _block_module(model)
    M, mb, L = tokens_mb.shape
    E = model.d_model
    rank = lax.axis_index(pipe_axis)
    positions = jnp.arange(L)
    is_first = rank == 0
    is_last = (rank == num_stages - 1).astype(jnp.float32)
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    # The exact stage-boundary modules TransformerLM uses, applied with the
    # pipeline's param slices — bit-identical numerics to the dense model.
    embed_mod = nn.Embed(model.vocab_size, E, dtype=model.compute_dtype)
    ln_f_mod = nn.LayerNorm(dtype=model.compute_dtype)
    head_mod = nn.Dense(model.vocab_size, dtype=model.compute_dtype)

    def embed(tok):
        return embed_mod.apply({"params": params["embed"]}, tok)

    def head_loss(x, tgt):
        h = ln_f_mod.apply({"params": params["ln_f"]}, x)
        logits = head_mod.apply({"params": params["lm_head"]}, h)
        return lm_cross_entropy(logits.astype(jnp.float32), tgt)

    # One lax.scan over the M+P−1 ticks: the body is traced once, so
    # program size (and compile time) is independent of the microbatch
    # count — tick-dependent behavior (injection window, peel-off window)
    # is expressed as masks on the traced tick index.
    def tick_core(act, loss_acc, t):
        # Stage 0 ingests microbatch t (clamped index; masked elsewhere).
        inject = embed(
            lax.dynamic_index_in_dim(tokens_mb, jnp.clip(t, 0, M - 1), keepdims=False)
        )
        x = jnp.where(is_first & (t < M), inject, act)
        y = _apply_local_span(block, params["blocks"], x, positions,
                              remat=_whole_layer_remat(model))
        # Last stage peels off microbatch m = t − (P−1).
        m = t - (num_stages - 1)
        tgt = lax.dynamic_index_in_dim(
            targets_mb, jnp.clip(m, 0, M - 1), keepdims=False
        )
        valid = ((m >= 0) & (m < M)).astype(jnp.float32)
        return y, loss_acc + is_last * valid * head_loss(y, tgt)

    def tick(carry, t):
        act, loss_acc = carry
        y, loss_acc = tick_core(act, loss_acc, t)
        return (lax.ppermute(y, pipe_axis, perm), loss_acc), None

    T = M + num_stages - 1
    act = jnp.zeros((mb, L, E), model.compute_dtype)
    loss_acc = jnp.zeros((), jnp.float32)
    # Scan the first T−1 ticks; the final tick runs outside the scan so its
    # output needs no (wasted) ppermute hop.
    (act, loss_acc), _ = lax.scan(tick, (act, loss_acc), jnp.arange(T - 1))
    _, loss_acc = tick_core(act, loss_acc, jnp.asarray(T - 1))
    # Local loss: non-zero on the last stage only.  The psum that shares it
    # with every stage happens OUTSIDE value_and_grad — a psum inside the
    # differentiated region would inflate cotangents by the axis size under
    # shard_map with replication-checking off (its transpose conservatively
    # psums the cotangent).
    return loss_acc / M


def _reject_lars(config) -> None:
    """Shared guard for every pipeline schedule: inside the shard_map
    each device's "blocks" leaves are only its stage's slice, so LARS's
    per-leaf norms would be stage-local and the trust ratios would
    change with the stage count — the same flat-slice inexactness
    ZeRO-1/FSDP refuse (zero1.py / fsdp.py)."""
    from distributed_machine_learning_tpu.train.lars import LARSConfig

    if type(config) is LARSConfig:
        raise ValueError(
            "LARS is not supported under pipeline/3-D parallelism: "
            "per-leaf weight/grad norms would be computed on per-stage "
            "slices; use sgd or adamw (elementwise updates are exact on "
            "any slice)"
        )


_BOUNDARY_MODULES = ("embed", "ln_f", "lm_head")


def _boundary_mom(momentum, take):
    """Apply ``take`` (a subtree selector/merger) across the momentum
    slot's two possible layouts: params-shaped (SGD) or a dict of
    params-shaped moment trees (AdamW's ``{"mu","nu"}``)."""
    if isinstance(momentum, dict) and "blocks" not in momentum:
        return {k: take(v) for k, v in momentum.items()}
    return take(momentum)


def _sharded_boundary_update(state: TrainState, grads, pipe_axis: str,
                             num_stages: int):
    """ZeRO-1-over-pipe for the replicated boundary modules: each stage
    updates only its 1/P slice of the flattened (embed, ln_f, lm_head)
    parameter+moment vectors, then ring-gathers the updated slices back
    to replicated — so the boundary update compute shards P-fold and
    the gathers' ppermute hops get async windows the scheduler fills
    with the (much larger) stacked-blocks update math: the pipeline
    flavor of the overlap-aware sharded weight update (arxiv
    2004.13336), with the gather hidden under the tail of the step
    instead of feeding ROOT as one sync collective.

    Bit-identical to the replicated update: the boundary grads arrive
    psum'd (same reduction as before), elementwise updates are exact on
    any slice of the flat vector, and the ring gather is pure data
    movement.  The moments stay REPLICATED in the state (the public
    TrainState layout is unchanged — this shards the update's compute
    and schedule, not its storage), so the updated moment slices ride
    the same ring home as the params.
    """
    from jax.flatten_util import ravel_pytree

    from distributed_machine_learning_tpu.ops.ring import (
        ring_all_gather_flat,
    )

    update_fn = update_fn_for_config(state.config)
    take = lambda t: {k: t[k] for k in _BOUNDARY_MODULES}

    flat_p, unravel_p = ravel_pytree(take(state.params))
    flat_g, _ = ravel_pytree(take(grads))
    mom_sub = _boundary_mom(state.momentum, take)
    if isinstance(mom_sub, dict) and "embed" not in mom_sub:
        # AdamW layout: one flat vector per moment tree.
        pairs = {k: ravel_pytree(v) for k, v in mom_sub.items()}
        flat_m = {k: p[0] for k, p in pairs.items()}
        unravel_m = {k: p[1] for k, p in pairs.items()}
    else:
        flat_m, unravel_m = ravel_pytree(mom_sub)

    n_elems = flat_p.shape[0]
    padded = -(-n_elems // num_stages) * num_stages
    shard_len = padded // num_stages
    rank = lax.axis_index(pipe_axis)
    pad = lambda v: jnp.pad(v, (0, padded - v.shape[0]))
    slice_of = lambda v: lax.dynamic_slice(
        pad(v), (rank * shard_len,), (shard_len,)
    )

    p_slice = slice_of(flat_p)
    g_slice = slice_of(flat_g)
    m_slice = jax.tree_util.tree_map(slice_of, flat_m)
    new_p_slice, new_m_slice = update_fn(
        p_slice, m_slice, g_slice, state.config, step=state.step
    )

    gather = lambda s: ring_all_gather_flat(
        s, pipe_axis, num_stages, n_buckets=2
    )[:n_elems]
    new_boundary_p = unravel_p(gather(new_p_slice))
    if isinstance(flat_m, dict):
        new_boundary_m = {
            k: unravel_m[k](gather(new_m_slice[k])) for k in flat_m
        }
    else:
        new_boundary_m = unravel_m(gather(new_m_slice))
    return new_boundary_p, new_boundary_m


def pp_grads_and_update(state: TrainState, loss_fn, pipe_axis,
                        grad_constraint=None, overlap_update=False,
                        num_stages=None):
    """Shared back half of every jax.grad-scheduled pipeline step (GPipe
    and interleaved): differentiate the forward-loss, share the
    last-stage loss, psum the boundary-module grads, update.

    Invariants that must hold for ANY schedule using this: the psums
    stay OUTSIDE value_and_grad (a psum inside the differentiated region
    would inflate cotangents by the axis size under shard_map with
    replication-checking off), and every replicated (non-"blocks") param
    — each stage holds a share that is zero unless it used the param —
    is summed here; stage-sharded blocks grads are already exact
    locally.

    ``grad_constraint``: optional ``grads -> grads`` hook applied
    between the backward and the update — the ZeRO-1 × 3-D step
    annotates the grads with their dp-sharded MOMENT layout here, so
    GSPMD reshards once at the update instead of propagating the moment
    sharding up into the stacked-layer backward scatter (see
    ``parallel3d.py``).

    ``overlap_update``: shard the boundary-module update over the pipe
    axis and ring-gather the updated slices (see
    :func:`_sharded_boundary_update`) — bit-identical math, with the
    boundary gather off the step's sync tail.  Requires ``num_stages``.
    """
    _reject_lars(state.config)
    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    loss = lax.psum(loss, pipe_axis)
    for name in _BOUNDARY_MODULES:
        grads[name] = jax.tree_util.tree_map(
            lambda g: lax.psum(g, pipe_axis), grads[name]
        )
    if grad_constraint is not None:
        grads = grad_constraint(grads)
    if overlap_update:
        if num_stages is None:
            raise ValueError("overlap_update requires num_stages")
        take_blocks = lambda t: {"blocks": t["blocks"]}
        blk_params, blk_mom = update_fn_for_config(state.config)(
            take_blocks(state.params),
            _boundary_mom(state.momentum, take_blocks),
            take_blocks(grads),
            state.config,
            step=state.step,
        )
        bnd_params, bnd_mom = _sharded_boundary_update(
            state, grads, pipe_axis, num_stages
        )
        new_params = {**bnd_params, **blk_params}

        def merge(blk, bnd):
            return {**bnd, **blk}

        if isinstance(state.momentum, dict) and "blocks" not in state.momentum:
            new_momentum = {
                k: merge(blk_mom[k], bnd_mom[k]) for k in state.momentum
            }
        else:
            new_momentum = merge(blk_mom, bnd_mom)
    else:
        new_params, new_momentum = update_fn_for_config(state.config)(
            state.params, state.momentum, grads, state.config,
            step=state.step
        )
    new_state = state.replace(
        params=new_params, momentum=new_momentum, step=state.step + 1
    )
    return new_state, loss


def _pp_step_impl(
    model, state: TrainState, tokens_mb, targets_mb, *, pipe_axis,
    num_stages, grad_constraint=None, overlap_update=False,
):
    loss_fn = partial(
        _pipeline_forward_loss,
        model,
        tokens_mb=tokens_mb,
        targets_mb=targets_mb,
        pipe_axis=pipe_axis,
        num_stages=num_stages,
    )
    return pp_grads_and_update(state, loss_fn, pipe_axis,
                               grad_constraint=grad_constraint,
                               overlap_update=overlap_update,
                               num_stages=num_stages)


def _state_specs(
    pipe_axis: str, params_example: dict, momentum_example=None
) -> TrainState:
    """shard_map PartitionSpec pytree for a pipeline TrainState."""

    def param_spec(tree, stacked: bool):
        return jax.tree_util.tree_map(
            lambda x: P(pipe_axis, *(None,) * (x.ndim - 1)) if stacked else P(),
            tree,
        )

    def specs(params):
        return {
            "embed": param_spec(params["embed"], False),
            "blocks": param_spec(params["blocks"], True),
            "ln_f": param_spec(params["ln_f"], False),
            "lm_head": param_spec(params["lm_head"], False),
        }

    p_specs = specs(params_example)
    return TrainState(
        params=p_specs,
        momentum=_moment_layout(p_specs, params_example, momentum_example),
        batch_stats={},
        step=P(),
        rng=P(),
        config=None,
    )


def make_pipeline_step(
    step_impl,
    model: TransformerLM,
    mesh: Mesh,
    num_microbatches: int,
    pipe_axis: str = PIPE_AXIS,
):
    """Shared pipeline step builder (GPipe and 1F1B): validation, the
    tree-structure-keyed jit cache, and the shard_map/donate wrapper
    around ``step_impl(model, state, tokens_mb, targets_mb, *,
    pipe_axis, num_stages)`` — one copy so the schedules cannot drift
    on anything but the schedule itself."""
    if model.attn_impl not in ("dense", "flash"):
        raise ValueError(
            "pipeline step supports attn_impl='dense' or 'flash' (the "
            "pipe-axis shard_map is fully manual, so the flash kernel "
            "runs on local shapes); sequence-sharded impls need a "
            "second mesh axis"
        )
    if pipe_axis not in mesh.axis_names:
        raise ValueError(f"mesh is missing axis {pipe_axis!r}: {mesh.axis_names}")
    num_stages = mesh.shape[pipe_axis]
    if model.n_layers % num_stages:
        raise ValueError(
            f"n_layers={model.n_layers} must divide evenly into "
            f"{num_stages} pipeline stages"
        )
    if num_microbatches < 1:
        raise ValueError("num_microbatches must be >= 1")

    impl = partial(
        step_impl, model, pipe_axis=pipe_axis, num_stages=num_stages
    )

    jitted: dict = {}

    def step(state: TrainState, tokens_mb, targets_mb):
        if tokens_mb.shape[0] != num_microbatches:
            raise ValueError(
                f"expected {num_microbatches} microbatches, got input shaped "
                f"{tokens_mb.shape} (use microbatch(tokens, targets, "
                f"{num_microbatches}))"
            )
        key = jax.tree_util.tree_structure(state)
        fn = jitted.get(key)
        if fn is None:
            state_spec = _state_specs(pipe_axis, state.params,
                                      state.momentum)
            state_spec = state_spec.replace(config=state.config)
            fn = jitted[key] = jax.jit(
                _shard_map(
                    impl,
                    mesh=mesh,
                    in_specs=(state_spec, P(), P()),
                    out_specs=(state_spec, P()),
                ),
                donate_argnums=(0,),
            )
        return fn(state, tokens_mb, targets_mb)

    return step


def make_pp_lm_train_step(
    model: TransformerLM,
    mesh: Mesh,
    num_microbatches: int,
    pipe_axis: str = PIPE_AXIS,
    overlap_update: bool = False,
):
    """Build the GPipe ``step(state, tokens_mb, targets_mb) ->
    (state, loss)``.

    ``tokens_mb``/``targets_mb``: [num_microbatches, mb, L] (see
    ``microbatch``).  ``state`` from ``init_pipeline_state`` +
    ``shard_pp_state``.  Requires ``n_layers % P == 0``.

    ``overlap_update=True``: shard the boundary-module (embed / ln_f /
    lm_head) optimizer update over the pipe axis and ring-gather the
    updated slices back (bit-identical math; the gather's ppermute hops
    overlap the stacked-blocks update — see
    :func:`_sharded_boundary_update`).
    """
    impl = (partial(_pp_step_impl, overlap_update=True)
            if overlap_update else _pp_step_impl)
    return make_pipeline_step(
        impl, model, mesh, num_microbatches, pipe_axis
    )


def shard_pp_state(
    state: TrainState, mesh: Mesh, pipe_axis: str = PIPE_AXIS
) -> TrainState:
    """Place a pipeline TrainState: blocks sharded over stages, rest
    replicated."""
    spec_state = _state_specs(pipe_axis, state.params, state.momentum)
    spec_state = spec_state.replace(config=state.config)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), state, spec_state
    )


def microbatch(tokens, targets, num_microbatches: int):
    """[B, L] → [M, B/M, L] microbatch stacks (GPipe input layout)."""
    B = tokens.shape[0]
    if B % num_microbatches:
        raise ValueError(
            f"batch {B} not divisible by num_microbatches={num_microbatches}"
        )
    shape = (num_microbatches, B // num_microbatches) + tokens.shape[1:]
    return (
        jnp.asarray(tokens).reshape(shape),
        jnp.asarray(targets).reshape(shape),
    )
