# dmlcheck-virtual-path: distributed_machine_learning_tpu/runtime/transport.py
"""DML014 firing case: the dedup-store membership check and the
reservation insert split across two lock scopes — a duplicate op can
pass the check before the original inserts, and the append
double-fires (the PR-12 bug the layer-3 dedup_inflight scenario
replays)."""
import threading


class TcpGangServer:
    def __init__(self):
        self._seen = {}
        self._seen_lock = threading.Lock()

    def dispatch(self, op_id, result):
        with self._seen_lock:
            known = op_id in self._seen
        if not known:
            with self._seen_lock:
                self._seen[op_id] = result
        return known
