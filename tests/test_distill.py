"""cli.distill — draft-from-target distillation (VERDICT r4 item 6):
one command from a target checkpoint to a servable speculative draft."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.train.lm_step import init_lm_state

TARGET = ["--d-model", "32", "--n-layers", "2", "--n-heads", "4",
          "--vocab", "64", "--seq-len", "16", "--batch-size", "8"]


def test_distill_step_learns_teacher(rng):
    """The distillation objective moves the student toward the teacher.
    The KD soft cross-entropy is lower-bounded by the teacher's own
    softened entropy (a random teacher sits near ln V, so the ABSOLUTE
    loss barely moves) — the learnable quantity is the gap above that
    floor, which overfitting one fixed batch must collapse."""
    from distributed_machine_learning_tpu.cli.distill import (
        make_distill_step,
    )

    T = 2.0
    teacher = TransformerLM(vocab_size=32, d_model=32, n_layers=2,
                            n_heads=4)
    student = TransformerLM(vocab_size=32, d_model=16, n_layers=1,
                            n_heads=2)
    tparams = init_lm_state(teacher).params
    state = init_lm_state(student, seed=3)
    step = make_distill_step(student, teacher, kd_weight=1.0,
                             ce_weight=0.0, kd_temperature=T)
    block = rng.integers(0, 32, (8, 17)).astype(np.int32)
    x, y = jnp.asarray(block[:, :-1]), jnp.asarray(block[:, 1:])
    # Floor: the teacher's softened entropy on this batch, x T^2.
    t_logits = teacher.apply({"params": tparams}, x).astype(jnp.float32)
    t_logp = jax.nn.log_softmax(t_logits / T, axis=-1)
    floor = float(
        -jnp.mean(jnp.sum(jnp.exp(t_logp) * t_logp, axis=-1)) * T * T
    )
    gap0 = None
    for i in range(150):
        state, (loss, kd, ce) = step(state, tparams, x, y)
        if i == 0:
            gap0 = float(kd) - floor
    gap = float(kd) - floor
    assert gap0 > 0 and gap < 0.3 * gap0, (gap, gap0, floor)


def test_distill_cli_end_to_end(tmp_path, capsys):
    """Train a tiny target (cli.lm), distill a draft from its checkpoint
    (cli.distill), then SERVE both through cli.generate --spec-gamma —
    the full one-command workflow the PERF.md table documents."""
    from distributed_machine_learning_tpu.cli.distill import (
        main as distill_main,
    )
    from distributed_machine_learning_tpu.cli.generate import (
        main as generate_main,
    )
    from distributed_machine_learning_tpu.cli.lm import main as lm_main

    tdir, ddir = str(tmp_path / "target"), str(tmp_path / "draft")
    lm_main(TARGET + ["--parallel", "dp", "--max-iters", "4",
                      "--ckpt-dir", tdir])
    capsys.readouterr()
    distill_main(TARGET + [
        "--target-ckpt-dir", tdir, "--ckpt-dir", ddir,
        "--draft-d-model", "16", "--draft-n-layers", "1",
        "--draft-n-heads", "2", "--max-iters", "6",
        "--compute-dtype", "float32",
    ])
    out = capsys.readouterr().out
    assert "draft checkpoint:" in out
    assert "iter 0: loss" in out

    generate_main([
        "--ckpt-dir", tdir, "--draft-ckpt-dir", ddir,
        "--spec-gamma", "2", "--max-new-tokens", "8",
        "--temperature", "0", "--vocab", "64",
        "--d-model", "32", "--n-layers", "2", "--n-heads", "4",
        "--draft-d-model", "16", "--draft-n-layers", "1",
        "--draft-n-heads", "2", "--prompt", "ab",
        "--compute-dtype", "float32",
    ])
    spec_out = capsys.readouterr().out
    assert "ab" in spec_out

    # The speculative stream must equal the plain greedy stream (same
    # checkpoint, same flags, no draft) — the CLI-level version of the
    # bitwise-parity invariant.
    generate_main([
        "--ckpt-dir", tdir, "--max-new-tokens", "8",
        "--temperature", "0", "--vocab", "64",
        "--d-model", "32", "--n-layers", "2", "--n-heads", "4",
        "--prompt", "ab", "--compute-dtype", "float32",
    ])
    plain_out = capsys.readouterr().out
    assert spec_out.splitlines()[-1] == plain_out.splitlines()[-1]


def test_distill_guards():
    from distributed_machine_learning_tpu.cli.distill import (
        make_distill_step,
    )

    t = TransformerLM(vocab_size=32, d_model=16, n_layers=1, n_heads=2)
    with pytest.raises(ValueError, match="kd_temperature"):
        make_distill_step(t, t, 1.0, 0.5, kd_temperature=0.0)
