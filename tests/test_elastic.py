"""Elastic gang recovery (ISSUE 5): topology-change-tolerant checkpoint
resharding and shrink-to-survivors continuation.

Fast half: ShardSpec/repad invariants, the exact (padding-free) data
partition, reshard round-trip property tests (save@N → restore@M →
save@M → restore@N bit-identical logical state) across the dp, zero1,
and fsdp layouts for both the VGG (SGD) and LM (AdamW) states, logical
manifest digests surviving resharding, the survivor-scoped election,
the ledger-driven lose_rank budget, the all-quarantined chain report,
and the offline reshard/verify tools.

Slow half (``slow`` + ``faultinject``): the acceptance chaos proof — a
4-worker gang with ``lose_rank@1:7`` finishes as a 3-worker gang with
exactly one shrink event, exact-once example consumption post-shrink,
and a final checkpoint that restores bit-exactly onto world sizes 1, 3,
and 4.
"""

from __future__ import annotations

import hashlib
import importlib.util
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.data.sharding import (
    exact_shard_indices,
    shard_indices,
)
from distributed_machine_learning_tpu.models.vgg import VGGTest
from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.parallel.fsdp import shard_fsdp_state
from distributed_machine_learning_tpu.parallel.zero1 import shard_zero1_state
from distributed_machine_learning_tpu.runtime.coordinator import (
    GangCoordinator,
    clear_gang_state,
    elect_restore_step,
)
from distributed_machine_learning_tpu.runtime.faults import (
    FAULT_LEDGER_FILE,
    FaultEvents,
    FaultInjector,
    corrupt_checkpoint_data,
    ledger_lost_ranks,
)
from distributed_machine_learning_tpu.runtime.mesh import (
    ShardSpec,
    padded_len,
    repad_flat,
)
from distributed_machine_learning_tpu.runtime.supervisor import (
    GangFailure,
    gang_supervise,
)
from distributed_machine_learning_tpu.train.adamw import AdamWConfig
from distributed_machine_learning_tpu.train.checkpoint import (
    CheckpointVerifyError,
    NoRestorableCheckpointError,
    checkpoint_chain_report,
    checkpoint_manifest,
    checkpoint_shard_spec,
    latest_checkpoint,
    quarantine_checkpoint,
    require_latest_checkpoint,
    reshard_restore,
    save_checkpoint,
    state_layout,
    validate_checkpoint,
)
from distributed_machine_learning_tpu.train.sgd import SGDConfig
from distributed_machine_learning_tpu.train.state import TrainState

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


# ---------------------------------------------------------------------------
# ShardSpec / repad_flat / exact_shard_indices
# ---------------------------------------------------------------------------


def test_shard_spec_validation_and_roundtrip():
    spec = ShardSpec("fsdp", world=4, n_elems=10)
    assert spec.padded == 12
    assert spec.with_world(3) == ShardSpec("fsdp", 3, n_elems=10)
    assert ShardSpec.from_dict(spec.as_dict()) == spec
    dp = ShardSpec("dp", world=2)
    assert dp.padded is None
    with pytest.raises(ValueError):
        ShardSpec("tensor", world=2)
    with pytest.raises(ValueError):
        ShardSpec("dp", world=0)
    with pytest.raises(ValueError):
        ShardSpec("zero1", world=2)  # flat layouts need n_elems


def test_repad_flat_preserves_logical_prefix():
    flat = np.arange(12, dtype=np.float32)  # 10 logical + 2 pad @ world 4
    out = repad_flat(flat, 10, 3)
    assert out.shape == (padded_len(10, 3),) == (12,)
    assert np.array_equal(out[:10], flat[:10])
    assert np.all(out[10:] == 0)
    back = repad_flat(out, 10, 4)
    assert np.array_equal(back[:10], flat[:10])
    with pytest.raises(ValueError):
        repad_flat(np.zeros((4,)), 10, 2)  # can't hold the logical prefix
    with pytest.raises(ValueError):
        repad_flat(np.zeros((4, 4)), 10, 2)  # not flat


@pytest.mark.parametrize("num,world", [(24, 1), (24, 3), (24, 4),
                                       (10, 3), (7, 8)])
def test_exact_shard_indices_partition_exactly_once(num, world):
    """The elastic-rebalance invariant: across ranks every index appears
    exactly once, with NO wrap padding — unlike shard_indices."""
    all_ids = [i for r in range(world)
               for i in exact_shard_indices(num, r, world)]
    assert sorted(all_ids) == list(range(num))
    sizes = [len(exact_shard_indices(num, r, world)) for r in range(world)]
    assert max(sizes) - min(sizes) <= 1


def test_exact_shard_indices_shuffle_is_world_invariant():
    """Shuffling permutes the GLOBAL epoch order identically for every
    world size — only the assignment of indices to ranks changes."""
    full = exact_shard_indices(24, 0, 1, shuffle=True, epoch=3)
    spread = np.empty(24, dtype=full.dtype)
    for r in range(3):
        spread[r::3] = exact_shard_indices(24, r, 3, shuffle=True, epoch=3)
    assert np.array_equal(full, spread)
    # Matches the torch-compatible sampler's permutation seed.
    assert np.array_equal(full, shard_indices(24, 0, 1, shuffle=True,
                                              epoch=3))


# ---------------------------------------------------------------------------
# Reshard round trips: dp / zero1 / fsdp x VGG (SGD) / LM (AdamW)
# ---------------------------------------------------------------------------


def _vgg_state():
    model = VGGTest()
    variables = model.init(jax.random.PRNGKey(69143),
                           jnp.zeros((1, 32, 32, 3)))
    return TrainState.create(
        params=jax.tree_util.tree_map(
            lambda x: jnp.array(x, copy=True), variables["params"]
        ),
        batch_stats=variables.get("batch_stats"),
        rng=jax.random.PRNGKey(7),
        config=SGDConfig(),
    )


def _lm_state():
    model = TransformerLM(vocab_size=32, d_model=16, n_layers=1, n_heads=2)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return TrainState.create(params=params, rng=jax.random.PRNGKey(9),
                             config=AdamWConfig())


@pytest.fixture(scope="module")
def base_states():
    return {"vgg": _vgg_state(), "lm": _lm_state()}


def _logical_flat(state, spec: ShardSpec):
    """(param logical vector, momentum logical tree) of a flat-shard
    state — the invariant a reshard must preserve bit for bit."""
    key = "param_shards" if spec.layout == "fsdp" else "param_flat"
    vec = np.asarray(getattr(state, key))[:spec.n_elems]
    mom = jax.tree_util.tree_map(
        lambda a: np.asarray(a)[:spec.n_elems], state.momentum_shards
    )
    return vec, mom


@pytest.mark.parametrize("model_name", ["vgg", "lm"])
@pytest.mark.parametrize("layout", ["dp", "zero1", "fsdp"])
def test_reshard_roundtrip_bit_identical(tmp_path, base_states, mesh8,
                                         mesh4, layout, model_name):
    """save@8 → restore@4 → save@4 → restore@8: the logical state is
    bit-identical after the double reshard, for every layout and both
    the CNN (SGD momentum tree) and LM (AdamW moment dict) states."""
    base = base_states[model_name]
    if layout == "dp":
        p1 = save_checkpoint(tmp_path / "a", base,
                             shard_spec=ShardSpec("dp", world=8))
        mid, spec_mid = reshard_restore(p1, world=4)
        p2 = save_checkpoint(tmp_path / "b", mid, shard_spec=spec_mid)
        back, spec_back = reshard_restore(p2, world=8)
        assert spec_back == ShardSpec("dp", world=8)
        for a, b in zip(jax.tree_util.tree_leaves(base.params),
                        jax.tree_util.tree_leaves(back.params)):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        return
    shard = shard_zero1_state if layout == "zero1" else shard_fsdp_state
    state8, _, n_elems = shard(base, mesh8)
    assert state_layout(state8) == layout
    spec8 = ShardSpec(layout, world=8, n_elems=n_elems)
    p1 = save_checkpoint(tmp_path / "a", state8, shard_spec=spec8)
    assert checkpoint_shard_spec(p1) == spec8

    state4, spec4 = reshard_restore(p1, mesh=mesh4)
    assert spec4 == spec8.with_world(4)
    assert type(state4).__name__ == type(state8).__name__
    assert np.asarray(state4.step).shape == ()
    p2 = save_checkpoint(tmp_path / "b", state4, shard_spec=spec4)
    state8b, spec8b = reshard_restore(p2, mesh=mesh8)
    assert spec8b == spec8

    vec0, mom0 = _logical_flat(state8, spec8)
    vec1, mom1 = _logical_flat(state8b, spec8b)
    assert np.array_equal(vec0, vec1)
    for a, b in zip(jax.tree_util.tree_leaves(mom0),
                    jax.tree_util.tree_leaves(mom1)):
        assert np.array_equal(a, b)
    # The logical manifest digests are identical across the two worlds:
    # corruption detection survives resharding.
    leaves1 = checkpoint_manifest(p1)["leaves"]
    leaves2 = checkpoint_manifest(p2)["leaves"]
    flat_key = "param_shards" if layout == "fsdp" else "param_flat"
    assert leaves1[flat_key]["logical_elems"] == n_elems
    assert leaves1[flat_key]["sha256"] == leaves2[flat_key]["sha256"]
    assert leaves1[flat_key]["bytes"] == leaves2[flat_key]["bytes"]


@pytest.mark.parametrize("small,big", [(3, 5), (4, 7)])
@pytest.mark.parametrize("layout", ["dp", "zero1", "fsdp"])
def test_reshard_grow_direction_ragged_worlds(tmp_path, base_states,
                                              mesh8, layout, small, big):
    """ISSUE 10 satellite: the GROW direction with ragged worlds —
    save@3→restore@5 and save@4→restore@7 (neither divides the element
    count), asserting logical bit-identity against the original state
    and that corruption is still caught across the grow."""
    base = base_states["lm"]
    ev = FaultEvents()
    if layout == "dp":
        p_small = save_checkpoint(tmp_path / "small", base,
                                  shard_spec=ShardSpec("dp", world=small))
        grown, spec = reshard_restore(p_small, world=big, events=ev)
        assert spec == ShardSpec("dp", world=big)
        assert ev.reshard_restores == 1
        for a, b in zip(jax.tree_util.tree_leaves(base.params),
                        jax.tree_util.tree_leaves(grown.params)):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        corrupt_checkpoint_data(p_small)
        with pytest.raises(CheckpointVerifyError):
            reshard_restore(p_small, world=big)
        return
    shard = shard_zero1_state if layout == "zero1" else shard_fsdp_state
    state8, _, n = shard(base, mesh8)
    spec8 = ShardSpec(layout, world=8, n_elems=n)
    p8 = save_checkpoint(tmp_path / "w8", state8, shard_spec=spec8)
    state_small, spec_small = reshard_restore(p8, world=small, events=ev)
    p_small = save_checkpoint(tmp_path / "small", state_small,
                              shard_spec=spec_small)
    grown, spec_big = reshard_restore(p_small, world=big, events=ev)
    assert spec_big == spec8.with_world(big)
    assert ev.reshard_restores == 2
    vec0, mom0 = _logical_flat(state8, spec8)
    vec1, mom1 = _logical_flat(grown, spec_big)
    assert np.array_equal(vec0, vec1)
    for a, b in zip(jax.tree_util.tree_leaves(mom0),
                    jax.tree_util.tree_leaves(mom1)):
        assert np.array_equal(a, b)
    # The logical digests survive BOTH ragged hops: a byte flip in the
    # small-world save is caught when restoring at the bigger world.
    leaves8 = checkpoint_manifest(p8)["leaves"]
    leaves_s = checkpoint_manifest(p_small)["leaves"]
    flat_key = "param_shards" if layout == "fsdp" else "param_flat"
    assert leaves8[flat_key]["sha256"] == leaves_s[flat_key]["sha256"]
    corrupt_checkpoint_data(p_small)
    with pytest.raises(CheckpointVerifyError):
        reshard_restore(p_small, world=big)


def test_ckpt_reshard_tool_grow_direction(tmp_path, base_states, mesh8,
                                          capsys):
    """The offline tool in the grow direction: a world-3 source rewrites
    to world 7 (both ragged) and restores bit-identically."""
    state8, _, n = shard_zero1_state(base_states["lm"], mesh8)
    w8 = tmp_path / "w8"
    save_checkpoint(w8, state8,
                    shard_spec=ShardSpec("zero1", world=8, n_elems=n))
    state3, spec3 = reshard_restore(w8 / "step_0", world=3)
    src = tmp_path / "src"
    save_checkpoint(src, state3, shard_spec=spec3)
    tool = _load_tool("ckpt_reshard")
    rc = tool.main([str(src), str(tmp_path / "dst"), "--world", "7"])
    assert rc == 0, capsys.readouterr().err
    dst = os.path.join(tmp_path, "dst", "step_0")
    assert validate_checkpoint(dst) == []
    assert checkpoint_shard_spec(dst) == ShardSpec("zero1", world=7,
                                                   n_elems=n)
    restored, _ = reshard_restore(dst, world=8)
    assert np.array_equal(np.asarray(restored.param_flat)[:n],
                          np.asarray(state8.param_flat)[:n])


def test_reshard_to_ragged_world_without_mesh(tmp_path, base_states,
                                              mesh8):
    """A world that does not divide the element count (and no mesh to
    place onto) still round-trips the logical state exactly."""
    state8, _, n = shard_zero1_state(base_states["lm"], mesh8)
    spec = ShardSpec("zero1", world=8, n_elems=n)
    p = save_checkpoint(tmp_path, state8, shard_spec=spec)
    ev = FaultEvents()
    state3, spec3 = reshard_restore(p, world=3, events=ev)
    assert ev.reshard_restores == 1
    assert state3.param_flat.shape == (padded_len(n, 3),)
    assert np.array_equal(np.asarray(state3.param_flat)[:n],
                          np.asarray(state8.param_flat)[:n])
    assert spec3.world == 3


def test_reshard_detects_corruption_across_worlds(tmp_path, base_states,
                                                  mesh8):
    """A byte flip in the saved payload is caught by the LOGICAL leaf
    digests even when restoring onto a different world size."""
    state8, _, n = shard_fsdp_state(base_states["vgg"], mesh8)
    p = save_checkpoint(tmp_path, state8,
                        shard_spec=ShardSpec("fsdp", world=8, n_elems=n))
    corrupt_checkpoint_data(p)
    with pytest.raises(CheckpointVerifyError):
        reshard_restore(p, world=4)


def test_sharded_save_requires_matching_spec(tmp_path, base_states,
                                             mesh8):
    state8, _, n = shard_fsdp_state(base_states["vgg"], mesh8)
    with pytest.raises(ValueError):
        save_checkpoint(tmp_path, state8)  # flat layout, no spec
    with pytest.raises(ValueError):
        save_checkpoint(tmp_path, state8,
                        shard_spec=ShardSpec("zero1", 8, n_elems=n))
    # A spec whose (world, n_elems) does not describe THIS state's
    # padded vectors would silently truncate parameters on reshard —
    # rejected at save time.
    with pytest.raises(ValueError):
        save_checkpoint(tmp_path, state8,
                        shard_spec=ShardSpec("fsdp", 8, n_elems=n - 8))
    with pytest.raises(ValueError):
        save_checkpoint(tmp_path, state8,
                        shard_spec=ShardSpec("fsdp", 4, n_elems=n))


def test_legacy_checkpoint_reshards_as_dp(tmp_path):
    """Spec-less (pre-elastic) checkpoints restore at any world: they
    were never world-padded."""
    state = TrainState.create(params={"w": jnp.arange(4, dtype=jnp.float32)})
    p = save_checkpoint(tmp_path, state)
    assert checkpoint_shard_spec(p) is None
    restored, spec = reshard_restore(p, world=5)
    assert spec.layout == "dp" and spec.world == 5
    assert np.array_equal(np.asarray(restored.params["w"]),
                          np.asarray(state.params["w"]))


# ---------------------------------------------------------------------------
# All-quarantined fallback chain: the per-candidate verdict report
# ---------------------------------------------------------------------------


def test_chain_report_and_require_latest(tmp_path):
    state = TrainState.create(params={"w": jnp.zeros((4,), jnp.float32)})
    p0 = save_checkpoint(tmp_path, state)
    p1 = save_checkpoint(tmp_path, state.replace(step=state.step + 5))
    assert require_latest_checkpoint(tmp_path) == p1
    quarantine_checkpoint(p0, "torn on host 2")
    quarantine_checkpoint(p1, "gang election verdict")
    report = checkpoint_chain_report(tmp_path)
    assert [os.path.basename(p) for p, _ in report] == ["step_5", "step_0"]
    assert all(v.startswith("quarantined") for _, v in report)
    with pytest.raises(NoRestorableCheckpointError) as err:
        require_latest_checkpoint(tmp_path)
    msg = str(err.value)
    # Every candidate is named with its quarantine reason — not a bare
    # "no checkpoint found".
    assert "step_5" in msg and "gang election verdict" in msg
    assert "step_0" in msg and "torn on host 2" in msg
    with pytest.raises(NoRestorableCheckpointError) as err:
        require_latest_checkpoint(tmp_path / "empty")
    assert "no step_<n> directories exist" in str(err.value)


# ---------------------------------------------------------------------------
# Survivor-scoped election + ledger retention across a shrink
# ---------------------------------------------------------------------------


def test_elect_restore_step_among_survivors(tmp_path):
    coords = [GangCoordinator(tmp_path, rank=r, world=3,
                              heartbeat_interval_s=0.1, peer_timeout_s=0.5)
              for r in range(3)]
    coords[0].record_valid_step(5)
    coords[2].record_valid_step(5)
    coords[0].record_valid_step(10)
    coords[2].record_valid_step(10)
    # Rank 1 never recorded anything (it is the dead one): the full
    # election cannot agree, the survivor election can.
    assert elect_restore_step(tmp_path, 3) is None
    assert elect_restore_step(tmp_path, 3, ranks=[0, 2]) == 10


def test_clear_gang_state_keeps_ledger_across_shrink(tmp_path):
    c = GangCoordinator(tmp_path, rank=0, world=1,
                        heartbeat_interval_s=0.1, peer_timeout_s=0.5)
    c.record_valid_step(5)
    ledger = tmp_path / FAULT_LEDGER_FILE
    ledger.write_text(json.dumps(
        {"index": 0, "kind": "lose_rank", "at": 7, "rank": 1}) + "\n")
    consumed = tmp_path / "consumed_rank0.jsonl"
    consumed.write_text("{}\n")
    # The shrink clear: records go (rank numbering changes); the ledger
    # stays (renumbered survivors must not re-fire latched faults) and
    # so does the consumption audit trail (whole-run history).
    clear_gang_state(tmp_path, restore_records=True, fault_ledger=False)
    assert not list(tmp_path.glob("restore_rank*"))
    assert ledger.exists() and consumed.exists()
    assert ledger_lost_ranks(ledger) == {1}
    clear_gang_state(tmp_path, restore_records=True)  # fresh run: all gone
    assert not ledger.exists()
    assert not consumed.exists()  # stale audit trails don't pollute
    assert ledger_lost_ranks(ledger) == set()


def test_lose_rank_grammar_and_targeting():
    inj = FaultInjector.parse("lose_rank@1:7", rank=0)
    assert inj.pending() == ["lose_rank@1:7"]
    # Non-target rank: latched without acting.
    assert list(inj.wrap_batches(range(9), FaultEvents())) == list(range(9))
    assert inj.pending() == []
    with pytest.raises(ValueError):
        FaultInjector.parse("lose_rank@7")  # missing rank
    with pytest.raises(ValueError):
        FaultInjector.parse("lose_rank@1:7:2.0")  # too many fields


# ---------------------------------------------------------------------------
# gang_supervise: budget attribution + shrink (stub workers, no jax)
# ---------------------------------------------------------------------------


def _stub_worker_cmd(tmp_path, body: str):
    """A worker argv factory whose subprocess runs ``body`` with RANK /
    ATTEMPT / WORLD / ORIG env-style format substitutions — cheap
    processes, no jax import."""

    def worker_cmd(rank, attempt, world, orig_rank):
        code = body.format(rank=rank, attempt=attempt, world=world,
                           orig=orig_rank, root=str(tmp_path))
        return [sys.executable, "-c", code]

    return worker_cmd


def test_gang_supervise_shrinks_on_lose_rank_ledger(tmp_path):
    """Attempt 0: rank 1 writes a lose_rank ledger entry and dies hard;
    the supervisor must shrink to [0, 2] (renumbered 0..1) and the
    relaunched gang finishes — with the shrink counted."""
    gang = tmp_path / "gang"
    body = (
        "import json, os, sys\n"
        "rank, attempt, world, orig = {rank}, {attempt}, {world}, {orig}\n"
        "open(os.path.join({root!r}, 'seen.jsonl'), 'a').write(\n"
        "    json.dumps(dict(rank=rank, attempt=attempt, world=world,\n"
        "                    orig=orig)) + '\\n')\n"
        "if attempt == 0 and rank == 1:\n"
        "    with open(os.path.join({root!r}, 'gang',\n"
        "                           'faults_fired.jsonl'), 'a') as f:\n"
        "        f.write(json.dumps(dict(index=0, kind='lose_rank',\n"
        "                                at=7, rank=1)) + '\\n')\n"
        "    os._exit(23)\n"
        "sys.exit(0)\n"
    )
    events = FaultEvents()
    codes = gang_supervise(
        _stub_worker_cmd(tmp_path, body), 3, gang,
        min_world=1, events=events, poll_s=0.05, max_restarts=2,
    )
    assert codes == [0, 0]
    assert events.gang_shrinks == 1 and events.gang_restarts == 1
    seen = [json.loads(line)
            for line in (tmp_path / "seen.jsonl").read_text().splitlines()]
    final = [s for s in seen if s["attempt"] == 1]
    # Survivors renumbered 0..1 in original order, world shrunk to 2.
    assert sorted((s["rank"], s["orig"]) for s in final) == [(0, 0), (1, 2)]
    assert all(s["world"] == 2 for s in final)


def test_gang_supervise_budget_exhaustion_without_shrink_fails(tmp_path):
    """rank_restart_budget with shrinking disabled: an unrecoverable
    rank is terminal, not an infinite relaunch loop."""
    body = (
        "import os, sys\n"
        "os._exit(9) if {rank} == 1 else sys.exit(0)\n"
    )
    events = FaultEvents()
    with pytest.raises(GangFailure) as err:
        gang_supervise(
            _stub_worker_cmd(tmp_path, body), 2, tmp_path / "gang",
            rank_restart_budget=0, events=events, poll_s=0.05,
            max_restarts=5,
        )
    assert "unrecoverable" in str(err.value)
    assert events.gang_shrinks == 0


def test_gang_supervise_legacy_two_arg_worker_cmd(tmp_path):
    """Pre-elastic closures (rank, attempt) keep working — including
    ones with trailing keyword-only options, which must not be
    mistaken for elastic (world-accepting) signatures."""

    def worker_cmd(rank, attempt):
        return [sys.executable, "-c", "import sys; sys.exit(0)"]

    assert gang_supervise(worker_cmd, 2, tmp_path / "gang",
                          poll_s=0.05) == [0, 0]

    def kw_cmd(rank, attempt, *, verbose=False, **extra):
        return [sys.executable, "-c", "import sys; sys.exit(0)"]

    assert gang_supervise(kw_cmd, 2, tmp_path / "gang2",
                          poll_s=0.05) == [0, 0]
    # And a shrink-enabled run refuses a closure that can't be told
    # the post-shrink world size.
    with pytest.raises(ValueError):
        gang_supervise(worker_cmd, 2, tmp_path / "gang3", min_world=1)


# ---------------------------------------------------------------------------
# Offline tools: ckpt_reshard + ckpt_verify --json
# ---------------------------------------------------------------------------


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_ckpt_reshard_tool_rewrites_layout(tmp_path, base_states, mesh8,
                                           capsys):
    state8, _, n = shard_zero1_state(base_states["lm"], mesh8)
    src = tmp_path / "src"
    save_checkpoint(src, state8, cursor=11,
                    shard_spec=ShardSpec("zero1", world=8, n_elems=n))
    tool = _load_tool("ckpt_reshard")
    rc = tool.main([str(src), str(tmp_path / "dst"), "--world", "5"])
    assert rc == 0, capsys.readouterr().err
    dst = os.path.join(tmp_path, "dst", "step_0")
    assert validate_checkpoint(dst) == []
    spec = checkpoint_shard_spec(dst)
    assert spec == ShardSpec("zero1", world=5, n_elems=n)
    restored, _ = reshard_restore(dst, world=8)
    assert np.array_equal(np.asarray(restored.param_flat)[:n],
                          np.asarray(state8.param_flat)[:n])
    from distributed_machine_learning_tpu.train.checkpoint import (
        checkpoint_cursor,
    )

    assert checkpoint_cursor(dst) == 11  # config payload carried over
    # An unrestorable source reports per-candidate verdicts, rc 1.
    quarantine_checkpoint(src / "step_0", "test verdict")
    rc = tool.main([str(src), str(tmp_path / "dst2"), "--world", "3"])
    captured = capsys.readouterr()
    assert rc == 1 and "test verdict" in captured.err


def test_ckpt_verify_json_summary(tmp_path):
    state = TrainState.create(params={"w": jnp.zeros((8,), jnp.float32)})
    save_checkpoint(tmp_path, state,
                    shard_spec=ShardSpec("dp", world=4))
    p1 = save_checkpoint(tmp_path, state.replace(step=state.step + 5))
    corrupt_checkpoint_data(p1)
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ckpt_verify.py"),
         str(tmp_path), "--json"],
        capture_output=True, text=True, timeout=60,
    )
    assert res.returncode == 1, res.stdout + res.stderr
    payload = json.loads(res.stdout)
    assert payload["total"] == 2 and payload["invalid"] == 1
    by_name = {os.path.basename(c["path"]): c
               for c in payload["checkpoints"]}
    assert by_name["step_0"]["ok"] is True
    assert by_name["step_0"]["shard_spec"] == {"layout": "dp", "world": 4,
                                               "n_elems": None}
    assert by_name["step_5"]["ok"] is False
    assert by_name["step_5"]["status"] == "CORRUPT"
    assert by_name["step_5"]["bad_files"]


# ---------------------------------------------------------------------------
# Chaos: the 4-worker gang shrinking to 3 survivors (multi-process)
# ---------------------------------------------------------------------------


def _run_gang(root, *, faults=None, workers=4, steps=12, save_every=5,
              peer_timeout=6.0, telemetry=False, timeout=280,
              extra=()):
    from distributed_machine_learning_tpu.cli.gang import (
        scrubbed_worker_env,
    )

    cmd = [
        sys.executable, "-m", "distributed_machine_learning_tpu.cli.gang",
        "--workers", str(workers), "--steps", str(steps),
        "--save-every", str(save_every),
        "--ckpt-dir", os.path.join(root, "ckpt"),
        "--gang-dir", os.path.join(root, "gang"),
        "--peer-timeout", str(peer_timeout),
        *extra,
    ]
    if faults:
        cmd += ["--faults", faults]
    if telemetry:
        cmd += ["--telemetry-dir", os.path.join(root, "telemetry")]
    return subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout,
        env=scrubbed_worker_env(REPO), cwd=REPO,
    )


def _consumed_records(root):
    gang = os.path.join(root, "gang")
    recs = []
    for name in os.listdir(gang):
        if name.startswith("consumed_rank"):
            with open(os.path.join(gang, name)) as f:
                for line in f:
                    recs.append(json.loads(line))
    return recs


@pytest.mark.slow
@pytest.mark.faultinject
def test_gang_shrinks_to_survivors_on_lose_rank(tmp_path):
    """ISSUE 5's acceptance bar: with lose_rank@1:7 on a 4-worker gang,
    rank 1 is lost for good at step 7, the supervisor shrinks to the 3
    survivors (exactly one shrink event), every training example is
    still consumed exactly once per step post-shrink (at the rebalanced
    world-3 shard assignment and rescaled per-host batch), and the
    final checkpoint restores bit-exactly onto world sizes 1, 3, and 4
    — verified via the manifest leaf digests."""
    root = str(tmp_path / "chaos")
    res = _run_gang(root, faults="lose_rank@1:7", telemetry=True)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "shrinking to 3 survivor(s)" in res.stdout
    assert "world size 3" in res.stdout
    assert "1 shrink(s)" in res.stdout

    # Exactly one shrink event, visible as a counter (not just a log).
    with open(os.path.join(root, "telemetry", "registry.json")) as f:
        snapshot = json.load(f)
    counters = {c["name"]: c["value"] for c in snapshot["counters"]}
    assert counters["gang_shrinks"] == 1
    assert counters["gang_restarts"] == 1
    gauges = {g["name"]: g["value"] for g in snapshot.get("gauges", [])}
    assert gauges.get("gang_world_size") == 3

    # The loss occurred: rank 1's attempt-0 log records the hard exit,
    # and no attempt-1 log exists for a 4th rank.
    logs = os.path.join(root, "gang", "logs")
    with open(os.path.join(logs, "rank1.attempt0.log")) as f:
        assert "permanent loss" in f.read()
    assert not os.path.exists(os.path.join(logs, "rank3.attempt1.log"))

    # Exact-once consumption per step, judged in the attempt that
    # finally completed each step: world 4 before the fault, world 3
    # after the shrink — every global example id exactly once.
    B = 24
    by_step: dict[int, list] = {}
    for r in _consumed_records(root):
        by_step.setdefault(r["step"], []).append(r)
    assert sorted(by_step) == list(range(12))
    saw_world3 = False
    for step, rows in by_step.items():
        final_attempt = max(r["attempt"] for r in rows)
        final = [r for r in rows if r["attempt"] == final_attempt]
        ids = sorted(i for r in final for i in r["ids"])
        assert ids == list(range(step * B, (step + 1) * B)), (
            f"step {step}: examples not consumed exactly once"
        )
        worlds = {r["world"] for r in final}
        assert len(worlds) == 1
        if worlds == {3}:
            saw_world3 = True
            # Rescaled per-host batch: 24/3 = 8 examples per rank.
            assert {len(r["ids"]) for r in final} == {8}
    assert saw_world3, "no step was consumed at the shrunken world size"

    # The final checkpoint restores bit-exactly onto 1, 3, and 4
    # workers; reshard_restore verifies the manifest leaf digests
    # against the logical arrays on every one of these restores.
    digests = {}
    for orig_rank in (0, 2, 3):
        latest = latest_checkpoint(
            os.path.join(root, "ckpt", f"rank{orig_rank}")
        )
        assert latest is not None and latest.endswith("step_12")
        for w in (1, 3, 4):
            state, spec = reshard_restore(latest, world=w)
            assert spec.world == w
            digests[(orig_rank, w)] = hashlib.sha256(
                np.ascontiguousarray(
                    np.asarray(state.params["w"])
                ).tobytes()
            ).hexdigest()
    assert len(set(digests.values())) == 1, digests
    # And the workers' own final-param digests agree across ranks.
    finals = set()
    for name in os.listdir(logs):
        with open(os.path.join(logs, name)) as f:
            for line in f:
                if line.startswith("final "):
                    finals.add(line.split()[1])
    assert len(finals) == 1

    # Every rank's checkpoint chain verifies end to end — via the JSON
    # summary the supervisor/CI consumes.
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ckpt_verify.py"),
         os.path.join(root, "ckpt"), "--json"],
        capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert json.loads(res.stdout)["invalid"] == 0
