"""The hand-rolled ppermute ring vs lax.psum/pmean (SURVEY.md §4d):
property tests on an 8-device CPU mesh."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

from distributed_machine_learning_tpu.ops.ring import (
    ring_all_reduce,
    ring_all_reduce_flat,
)


def _run_on_mesh(mesh, fn, per_device_inputs):
    """shard_map a per-device fn over stacked inputs (leading axis = device)."""
    wrapped = shard_map(
        fn, mesh=mesh, in_specs=P("batch"), out_specs=P("batch"), check_vma=False
    )
    return jax.jit(wrapped)(per_device_inputs)


@pytest.mark.parametrize("length", [1, 7, 8, 64, 1000, 4097])
@pytest.mark.parametrize("mean", [False, True])
def test_ring_flat_matches_psum(mesh8, length, mean, rng):
    n = 8
    data = rng.standard_normal((n, length)).astype(np.float32)
    expected = data.sum(axis=0) / (n if mean else 1)

    def per_device(x):
        x = x.reshape(-1)  # shard has leading dim 1
        out = ring_all_reduce_flat(x, "batch", n, mean=mean)
        return out[None]

    result = _run_on_mesh(mesh8, per_device, jnp.asarray(data))
    # Every device must hold the same full reduction.
    for d in range(n):
        np.testing.assert_allclose(
            np.asarray(result[d]), expected, rtol=1e-5, atol=1e-5
        )


@pytest.mark.parametrize("bucket_bytes", [64, 1024, 25 * 2**20])
def test_ring_pytree_bucketing(mesh8, bucket_bytes, rng):
    n = 8
    tree_shapes = {"w": (33, 17), "b": (129,), "k": (3, 3, 4, 8)}
    data = {
        k: rng.standard_normal((n, *s)).astype(np.float32)
        for k, s in tree_shapes.items()
    }

    def per_device(tree):
        local = jax.tree_util.tree_map(lambda x: x[0], tree)
        out = ring_all_reduce(
            local, "batch", n, mean=True, bucket_bytes=bucket_bytes
        )
        return jax.tree_util.tree_map(lambda x: x[None], out)

    wrapped = shard_map(
        per_device, mesh=mesh8, in_specs=P("batch"), out_specs=P("batch"),
        check_vma=False,
    )
    result = jax.jit(wrapped)(jax.tree_util.tree_map(jnp.asarray, data))
    for k in tree_shapes:
        expected = data[k].sum(axis=0) / n
        for d in range(n):
            np.testing.assert_allclose(
                np.asarray(result[k][d]), expected, rtol=1e-5, atol=1e-5
            )


def test_ring_matches_pmean_collective(mesh4, rng):
    """Direct head-to-head vs lax.pmean on the same mesh (world size 4 —
    the reference cluster size)."""
    n = 4
    data = rng.standard_normal((n, 513)).astype(np.float32)

    def per_device(x):
        x = x.reshape(-1)
        ours = ring_all_reduce_flat(x, "batch", n, mean=True)
        theirs = lax.pmean(x, "batch")
        return (ours - theirs)[None]

    diff = _run_on_mesh(mesh4, per_device, jnp.asarray(data))
    np.testing.assert_allclose(np.asarray(diff), 0.0, atol=1e-6)


def test_ring_single_device_identity():
    x = jnp.arange(10.0)
    assert np.allclose(ring_all_reduce_flat(x, "batch", 1), x)
