"""Per-layer (GSPMD) FSDP — ZeRO-3 with gather/compute overlap.

The flat-vector scheme (``parallel/fsdp.py``) all-gathers the ENTIRE
parameter vector in one collective before any forward work starts: one
serial ICI prelude on the critical path, and the full parameter vector
resident in HBM for the whole step.  That is the simplest correct
ZeRO-3, but it forfeits the overlap that makes FSDP scale — the
reference's own DDP gets its gradient comm overlapped with backward
compute via hooks (``/root/reference/part3/main.py:137``, group25.pdf
p.6), and a sharded-parameter scheme should earn the same on the
forward side.

This module is the TPU-native way to get that overlap: declare WHERE
each parameter lives — every leaf sharded 1/N along its largest
N-divisible dimension over the data axis — and ``jit`` the unmodified
train step with those in/out shardings.  XLA's SPMD partitioner then
inserts one all-gather per parameter AT ITS USE SITE (layer i's weights
are gathered when layer i runs, not before the step), keeps the
gradient w.r.t. each leaf in the sharded layout (a reduce-scatter, not
an all-reduce, since the update consumes the shard), and runs the
sharded optimizer update leaf-by-leaf.  The latency-hiding scheduler
overlaps layer i+1's gather with layer i's compute — the prefetch
pipeline hand-written FSDP implementations build manually, obtained
from the compiler.  The full parameter set is never resident as one
buffer: gathered weights live only across their use (and the backward's
re-use, scheduler-controlled), so peak parameter HBM is O(layer working
set), not O(P).

Versus the flat scheme (kept for the CNN path and as the simplest
correct baseline):

- flat: 1 gather + 1 reduce-scatter of one contiguous buffer; zero
  overlap; full params resident all step.  Trivially model-agnostic.
- per-layer: one gather per leaf, overlapped; params resident one
  layer at a time; same total bytes on the wire (all-gather + reduce-
  scatter of P elements each).

Both pair naturally with AdamW, whose two fp32 moment vectors are the
memory ZeRO exists to shard; the moments inherit their parameter's
spec.  Elementwise optimizers only (SGD/AdamW) — per-leaf sharding
keeps every leaf's slices aligned, but LARS's per-layer norms would
still need a per-leaf psum; excluded for parity with the flat scheme.

Flash attention composes: the builder clones the model with
``flash_mesh`` set, which routes the kernel through a fully-manual
``shard_map`` with the batch dim sharded (``models/transformer.py``) — the
Mosaic custom call then operates on local per-device shapes and never
meets the GSPMD partitioner, on any backend.  Sequence-sharded
attention (ring/ulysses) still needs a second mesh axis and stays
unsupported here.
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_machine_learning_tpu.parallel.gspmd import (
    make_cached_sharded_step,
    shard_state,
)
from distributed_machine_learning_tpu.runtime.mesh import BATCH_AXIS
from distributed_machine_learning_tpu.train.lars import LARSConfig
from distributed_machine_learning_tpu.train.lm_step import _lm_step_impl
from distributed_machine_learning_tpu.train.state import TrainState


def fsdp_pl_spec_for(n: int, data_axis: str = BATCH_AXIS):
    """Shape-keyed ZeRO-3 rule: shard each leaf's largest N-divisible
    dimension over the data axis; leaves with no divisible dim (biases
    of odd width, scalars) replicate — they are the O(d) minority.

    Unlike the TP rules this is deliberately semantics-free: ZeRO
    shards for MEMORY, and any dim slicing is valid because the leaf is
    gathered whole before use.  Picking the largest dim minimizes the
    replicated remainder and keeps gather messages big (ICI likes fat
    transfers)."""

    def spec_for(path, shape):
        del path
        best = None
        for i, d in enumerate(shape):
            if d % n == 0 and d >= n and (best is None or d > shape[best]):
                best = i
        if best is None:
            return P(*(None,) * len(shape))
        axes = [None] * len(shape)
        axes[best] = data_axis
        return P(*axes)

    return spec_for


def shard_fsdp_pl_state(
    state: TrainState, mesh: Mesh, data_axis: str = BATCH_AXIS
) -> TrainState:
    """Place a replicated TrainState into the per-layer ZeRO-3 layout
    (params + moments sharded per ``fsdp_pl_spec_for``)."""
    if isinstance(state.config, LARSConfig):
        raise ValueError(
            "per-layer FSDP cannot shard LARS (per-layer norms need a "
            "cross-shard reduction); use sgd or adamw"
        )
    return shard_state(state, mesh, fsdp_pl_spec_for(mesh.shape[data_axis],
                                                     data_axis))


def make_fsdp_pl_lm_train_step(
    model,
    mesh: Mesh,
    data_axis: str = BATCH_AXIS,
    fused_ce_chunks: int | None = None,
):
    """Build the per-layer-FSDP LM train step.

    ``state`` must be placed via :func:`shard_fsdp_pl_state`;
    tokens/targets sharded over ``data_axis``
    (``tensor_parallel.shard_tp_batch`` works).  Returns
    ``step(state, tokens, targets) -> (state, loss)``.
    """
    if model.attn_impl in ("flash", "auto") and model.flash_mesh is None:
        # Flash composes with this GSPMD step via the model's
        # fully-manual shard_map wrap (transformer.Attention.flash_mesh)
        # — the Mosaic custom call then sees local shapes and never
        # meets the partitioner.  Parameter structure is attn-agnostic,
        # so cloning here leaves the caller's init/state untouched.
        model = model.clone(flash_mesh=mesh, flash_batch_axis=data_axis)
    elif model.attn_impl not in ("dense", "flash", "auto"):
        raise ValueError(
            "per-layer FSDP supports dense/flash/auto attention "
            "(sequence-sharded ring/ulysses need a second mesh axis)"
        )
    if data_axis not in mesh.axis_names:
        raise ValueError(f"mesh is missing axis {data_axis!r}: "
                         f"{mesh.axis_names}")
    batch_sharding = NamedSharding(mesh, P(data_axis, None))
    impl = partial(_lm_step_impl, model, axis_names=(),
                   fused_ce_chunks=fused_ce_chunks)
    return make_cached_sharded_step(
        impl, mesh, fsdp_pl_spec_for(mesh.shape[data_axis], data_axis),
        batch_sharding,
    )


def fsdp_pl_sharded_fraction(state: TrainState, mesh: Mesh,
                             data_axis: str = BATCH_AXIS) -> float:
    """Fraction of parameter elements the rule actually shards —
    diagnostic for tests and sizing (biases of non-divisible width
    replicate; everything else shards)."""
    n = mesh.shape[data_axis]
    rule = fsdp_pl_spec_for(n, data_axis)
    total = sharded = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(state.params):
        keys = tuple(getattr(k, "key", str(k)) for k in path)
        size = leaf.size
        total += size
        if any(a is not None for a in rule(keys, tuple(leaf.shape))):
            sharded += size
    return sharded / max(total, 1)
