"""Benchmark harness — prints ONE JSON line for the driver.

Flagship workload: VGG-11/CIFAR-10 train steps (the reference's part1
measurement: 39 timed iterations at batch 256, iteration 0 excluded —
``part1/main.py:32-58``; 2.39 s/iter on its CPU node, group25.pdf p.2).

Metric: images/sec through the train step.  ``vs_baseline`` compares
against the reference's measured part1 rate (256 / 2.39 s ≈ 107.1
imgs/sec — BASELINE.md).

Measurement design: the 39 iterations run as ONE jitted ``lax.scan`` over
pre-staged device-resident batches, timed around a forced host fetch of
the final loss.  Per-step Python dispatch is excluded on purpose — on a
tunneled/remote TPU the dispatch round-trip (~100 ms here) would swamp a
~4 ms step and the naive per-step loop mis-measures by an order of
magnitude in either direction (async dispatch also returns before compute
finishes, so timing without a value fetch *under*-counts).  The scan
measures what the hardware actually does: 39 full fwd+bwd+update steps,
each on its own batch, augmentation included.  The trunk runs in bfloat16
(MXU-native; master weights and loss stay fp32).  Uses the synthetic
CIFAR stand-in when the real dataset is not on disk — identical
shapes/dtypes, so the throughput number is unaffected.
"""

from __future__ import annotations

import argparse
import json

import jax
import jax.numpy as jnp
import numpy as np

from distributed_machine_learning_tpu.bench.harness import timed_scan_epoch
from distributed_machine_learning_tpu.cli.common import init_model_and_state
from distributed_machine_learning_tpu.data.cifar10 import load_cifar10
from distributed_machine_learning_tpu.models.registry import get_model, list_models
from distributed_machine_learning_tpu.train.step import make_train_step

BATCH = 256  # part1/main.py:18
TIMED_ITERS = 39  # part1 protocol: 40 iters, iteration 0 excluded
BASELINE_IMGS_PER_SEC = 256 / 2.39  # group25.pdf p.2 → 107.1


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model", default="vgg11", choices=list_models())
    parser.add_argument("--reps", default=3, type=int,
                        help="timed repetitions; the best is reported")
    parser.add_argument("--chain", default=8, type=int,
                        help="chained scan dispatches per measurement; the "
                             "per-scan time is the (chain vs 1) slope, "
                             "cancelling the constant tunnel round-trip "
                             "(bench/harness.py)")
    args = parser.parse_args()
    model = get_model(args.model, compute_dtype=jnp.bfloat16)

    train = load_cifar10("./data", train=True)
    n = BATCH * TIMED_ITERS
    idx = np.arange(n) % len(train.labels)
    images = np.asarray(train.images)[idx].reshape(
        TIMED_ITERS, BATCH, *train.images.shape[1:]
    )
    labels = np.asarray(train.labels)[idx].reshape(TIMED_ITERS, BATCH)
    dx = jax.device_put(jnp.asarray(images))
    dy = jax.device_put(jnp.asarray(labels))

    step = make_train_step(model, augment=True, jit=False)
    state = init_model_and_state(model)
    tail: dict = {}
    best, _, _ = timed_scan_epoch(
        step, state, dx, dy, reps=args.reps, chain=args.chain, stats=tail
    )

    imgs_per_sec = BATCH * TIMED_ITERS / best
    # The reference measured only VGG-11 (group25.pdf p.2); comparing any
    # other model against that number would be apples-to-oranges.
    vs_baseline = (
        round(imgs_per_sec / BASELINE_IMGS_PER_SEC, 2)
        if args.model == "vgg11"
        else None
    )
    out = {
        "metric": f"{args.model}_cifar10_train_imgs_per_sec",
        "value": round(imgs_per_sec, 2),
        "unit": "imgs/sec",
        "vs_baseline": vs_baseline,
        # Tail latency per ITERATION over every raw scan sample (chain
        # points included): future BENCH_*.json rounds must report p95
        # next to the best/mean (docs/PERF.md) — a straggler-free best
        # hides exactly the steps a production run diagnoses by.
        "iter_p50_s": round(tail["p50_s"] / TIMED_ITERS, 6),
        "iter_p95_s": round(tail["p95_s"] / TIMED_ITERS, 6),
        "iter_p99_s": round(tail["p99_s"] / TIMED_ITERS, 6),
        "iter_max_s": round(tail["max_s"] / TIMED_ITERS, 6),
        "tail_samples": tail["samples"],
    }
    if args.model.startswith("vgg"):
        from distributed_machine_learning_tpu.models.vgg import _cfg
        from distributed_machine_learning_tpu.utils.flops import (
            mfu,
            vgg_train_flops_per_image,
        )

        flops = vgg_train_flops_per_image(_cfg[args.model.upper()])
        out["tflops_per_sec"] = round(imgs_per_sec * flops / 1e12, 1)
        out["mfu"] = round(mfu(imgs_per_sec * flops), 3)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
