# dmlcheck-virtual-path: distributed_machine_learning_tpu/runtime/x.py
"""DML012 firing cases: unbounded socket/HTTP IO in the runtime layer
— a monitor thread hung in a timeout-less connect can neither detect
peers nor join an abort."""
import socket
import urllib.request


def fetch_state(address):
    with socket.create_connection(address) as sock:
        sock.sendall(b"{}\n")
        return sock.recv(4096)


def fetch_page(url):
    return urllib.request.urlopen(url).read()


def fetch_api(host):
    from http.client import HTTPConnection

    return HTTPConnection(host)  # bare-import form, still unbounded


def raw_channel(host, port):
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.connect((host, port))
    return sock
